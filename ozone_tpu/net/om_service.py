"""OM gRPC service + remote client.

Mirrors the reference's OM client protocol surface (OmClientProtocol.proto
served by OzoneManagerProtocolServerSideTranslatorPB) at the verb level.
GrpcOmClient implements the same attribute surface OzoneClient needs from
OzoneManager, so the user-facing API works identically against a remote
OM (the RpcClient/GrpcOmTransport analog).
"""

from __future__ import annotations

import base64
import threading
from typing import Optional

from ozone_tpu import admission
from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.net import wire
from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.om.requests import OMError
from ozone_tpu.scm.pipeline import ReplicationConfig
from ozone_tpu.storage.ids import StorageError

SERVICE = "ozone.tpu.OmService"


class OmGrpcService:
    def __init__(self, om: OzoneManager, server: RpcServer,
                 addresses_provider=None, locations_provider=None):
        self.om = om
        # callable returning the dn_id -> address book (from the co-located
        # SCM service or a remote SCM client)
        self.addresses_provider = addresses_provider or (lambda: {})
        #: callable returning dn_id -> topology location, shipped with
        #: allocations so clients order replica reads nearest-first
        self.locations_provider = locations_provider
        #: HA leader gate, set by the daemon: raises
        #: StorageError("OM_NOT_LEADER", <leader address>) on followers so
        #: clients fail over. Reads are leader-gated too — followers
        #: apply committed entries asynchronously, so serving reads there
        #: would break read-your-writes (the reference routes all OM
        #: traffic to the Ratis leader the same way).
        self.gate = None
        #: HA barrier, set by the daemon: blocks until SCM decision
        #: records produced by a direct allocation are quorum-committed
        #: (the OM-request path gets this inside MetaHARing.submit_om)
        self.scm_barrier = None
        #: set by the HA daemon: callable returning this replica's
        #: applied log index, stamped on responses as `_applied` so
        #: shard-routing clients can carry a read-your-writes floor
        #: into lease-based follower reads
        self.applied_index_fn = None
        methods = {
                "CreateVolume": self._wrap(lambda m: self.om.create_volume(m["volume"])),
                "DeleteVolume": self._wrap(lambda m: self.om.delete_volume(m["volume"])),
                "VolumeInfo": self._wrap(lambda m: self.om.volume_info(m["volume"])),
                "SetVolumeOwner": self._wrap(
                    lambda m: self.om.set_volume_owner(m["volume"],
                                                       m["owner"])),
                "ListVolumes": self._wrap(lambda m: self.om.list_volumes()),
                "CreateBucket": self._wrap(
                    lambda m: self.om.create_bucket(
                        m["volume"], m["bucket"],
                        m.get("replication", "rs-6-3-1024k"),
                        m.get("layout", "OBJECT_STORE"),
                        encryption_key=m.get("encryption_key", ""),
                        gdpr=m.get("gdpr", False),
                    )
                ),
                "KmsCreateKey": self._wrap(
                    lambda m: self.om.kms_create_key(
                        m["name"], rotate=m.get("rotate", False))
                ),
                "KmsKeyInfo": self._wrap(
                    lambda m: self.om.kms_key_info(m["name"])
                ),
                "KmsListKeys": self._wrap(
                    lambda m: self.om.kms_list_keys()
                ),
                "KmsDecrypt": self._wrap(
                    lambda m: self.om.kms_decrypt(
                        m["volume"], m["bucket"], m["bundle"])
                ),
                "CreateBucketLink": self._wrap(
                    lambda m: self.om.create_bucket_link(
                        m["src_volume"], m["src_bucket"],
                        m["volume"], m["bucket"],
                    )
                ),
                "DeleteBucket": self._wrap(
                    lambda m: self.om.delete_bucket(m["volume"], m["bucket"])
                ),
                "BucketInfo": self._wrap(
                    lambda m: self.om.bucket_info(m["volume"], m["bucket"])
                ),
                "ListBuckets": self._wrap(
                    lambda m: self.om.list_buckets(m["volume"])
                ),
                "OpenKey": self._open_key,
                "AllocateBlock": self._allocate_block,
                "CommitKey": self._commit_key,
                "RecoverLease": self._recover_lease,
                "SetQuota": self._wrap(
                    lambda m: self.om.set_quota(
                        m["volume"], m.get("bucket", ""),
                        m.get("quota_bytes"),
                        m.get("quota_namespace"),
                    )
                ),
                "RepairQuota": self._wrap(
                    lambda m: self.om.repair_quota(m["volume"])
                ),
                "CreateSnapshot": self._wrap(
                    lambda m: self.om.create_snapshot(
                        m["volume"], m["bucket"], m["name"])
                ),
                "ListSnapshots": self._wrap(
                    lambda m: self.om.list_snapshots(
                        m["volume"], m["bucket"])
                ),
                "SnapshotInfo": self._wrap(
                    lambda m: self.om.snapshot_info(
                        m["volume"], m["bucket"], m["name"])
                ),
                "DeleteSnapshot": self._wrap(
                    lambda m: self.om.delete_snapshot(
                        m["volume"], m["bucket"], m["name"])
                ),
                "RenameSnapshot": self._wrap(
                    lambda m: self.om.rename_snapshot(
                        m["volume"], m["bucket"], m["name"],
                        m["new_name"])
                ),
                "SnapshotDiff": self._wrap(
                    lambda m: self.om.snapshot_diff(
                        m["volume"], m["bucket"], m["from_snapshot"],
                        m.get("to_snapshot"))
                ),
                "SnapshotKeys": self._wrap(
                    lambda m: self.om.snapshot_keys(
                        m["volume"], m["bucket"], m["name"])
                ),
                "SnapshotLookupKey": self._wrap(
                    lambda m: self.om.snapshot_lookup_key(
                        m["volume"], m["bucket"], m["name"], m["key"]),
                    with_addresses=True,
                ),
                "LookupKey": self._wrap(
                    lambda m: self.om.lookup_key(
                        m["volume"], m["bucket"], m["key"]),
                    with_addresses=True,
                ),
                "ListKeys": self._wrap(
                    lambda m: self.om.list_keys(
                        m["volume"], m["bucket"], m.get("prefix", ""),
                        m.get("start_after", ""), m.get("limit"),
                    )
                ),
                "DeleteKey": self._wrap(
                    lambda m: self.om.delete_key(
                        m["volume"], m["bucket"], m["key"],
                        expect_object_id=m.get("expect_object_id", ""),
                    )
                ),
                "RenameKey": self._wrap(
                    lambda m: self.om.rename_key(
                        m["volume"], m["bucket"], m["key"], m["new_key"]
                    )
                ),
                "SetKeyAttrs": self._wrap(
                    lambda m: self.om.set_key_attrs(
                        m["volume"], m["bucket"], m["key"], m["attrs"],
                        m.get("preconds"),
                    )
                ),
                "SetBucketAttrs": self._wrap(
                    lambda m: self.om.set_bucket_attrs(
                        m["volume"], m["bucket"], m["attrs"]
                    )
                ),
                # S3 secret + ACL verbs (reference OmClientProtocol
                # GetS3Secret/RevokeS3Secret/SetAcl/GetAcl)
                "GetS3Secret": self._wrap(
                    lambda m: self.om.get_s3_secret(
                        m["access_id"], m.get("create", True)
                    )
                ),
                "UpgradeStatus": self._wrap(
                    lambda m: self.om.upgrade_status()
                ),
                "RevokeS3Secret": self._wrap(
                    lambda m: self.om.revoke_s3_secret(m["access_id"])
                ),
                "SetBucketAcl": self._wrap(
                    lambda m: self.om.set_bucket_acl(
                        m["volume"], m["bucket"], m["acl"]
                    )
                ),
                "GetBucketAcl": self._wrap(
                    lambda m: self.om.get_bucket_acl(m["volume"], m["bucket"])
                ),
                # Multipart upload verbs (reference OmClientProtocol
                # InitiateMultiPartUpload/CommitMultiPartUpload/
                # CompleteMultiPartUpload/AbortMultiPartUpload/ListParts)
                "InitiateMultipartUpload": self._wrap(
                    lambda m: self.om.initiate_multipart_upload(
                        m["volume"], m["bucket"], m["key"],
                        m.get("replication"), m.get("metadata"),
                    )
                ),
                "MultipartInfo": self._wrap(
                    lambda m: self.om.multipart_info(
                        m["volume"], m["bucket"], m["key"], m["upload_id"]
                    )
                ),
                "CommitMultipartPart": self._commit_multipart_part,
                "CompleteMultipartUpload": self._wrap(
                    lambda m: self.om.complete_multipart_upload(
                        m["volume"], m["bucket"], m["key"], m["upload_id"],
                        m["parts"],
                    )
                ),
                "AbortMultipartUpload": self._wrap(
                    lambda m: self.om.abort_multipart_upload(
                        m["volume"], m["bucket"], m["key"], m["upload_id"]
                    )
                ),
                "ListParts": self._wrap(
                    lambda m: self.om.list_parts(
                        m["volume"], m["bucket"], m["key"], m["upload_id"]
                    )
                ),
                "ListMultipartUploads": self._wrap(
                    lambda m: self.om.list_multipart_uploads(
                        m["volume"], m["bucket"], m.get("prefix", "")
                    )
                ),
                # Native ACL + tenant verbs (reference OmClientProtocol
                # AddAcl/RemoveAcl/SetAcl/GetAcl + tenant admin RPCs)
                "ModifyAcl": self._wrap(
                    lambda m: self.om.modify_acl(
                        m["obj_type"], m["volume"], m.get("bucket", ""),
                        m.get("path", ""), m.get("op", "add"),
                        m.get("acls", []),
                    )
                ),
                "CheckAccess": self._wrap(
                    lambda m: self.om.check_access(
                        m["volume"], m.get("bucket"), m.get("key"),
                        m["right"], user=m.get("user"),
                        groups=m.get("groups", ()))
                ),
                "GetAcls": self._wrap(
                    lambda m: self.om.get_acls(
                        m["obj_type"], m["volume"], m.get("bucket", ""),
                        m.get("path", ""),
                    )
                ),
                "CreateTenant": self._wrap(
                    lambda m: self.om.create_tenant(
                        m["tenant"], m.get("volume", ""),
                        m.get("owner", "root"),
                    )
                ),
                "DeleteTenant": self._wrap(
                    lambda m: self.om.delete_tenant(m["tenant"])
                ),
                "ListTenants": self._wrap(lambda m: self.om.list_tenants()),
                "TenantAssignUser": self._wrap(
                    lambda m: self.om.tenant_assign_user(
                        m["tenant"], m["user"], m.get("access_id", "")
                    )
                ),
                "TenantRevokeAccess": self._wrap(
                    lambda m: self.om.tenant_revoke_access(m["access_id"])
                ),
                "ListTenantUsers": self._wrap(
                    lambda m: self.om.list_tenant_users(m["tenant"])
                ),
                "TenantForAccessId": self._wrap(
                    lambda m: self.om.tenant_for_access_id(m["access_id"])
                ),
                # FSO file-system verbs (reference OmClientProtocol
                # CreateDirectory/GetFileStatus/ListStatus/DeleteKey with
                # recursive flag)
                "CreateDirectory": self._wrap(
                    lambda m: self.om.create_directory(
                        m["volume"], m["bucket"], m["path"]
                    )
                ),
                "DeleteDirectory": self._wrap(
                    lambda m: self.om.delete_directory(
                        m["volume"], m["bucket"], m["path"],
                        m.get("recursive", False),
                    )
                ),
                "GetFileStatus": self._wrap(
                    lambda m: self.om.get_file_status(
                        m["volume"], m["bucket"], m["path"]
                    )
                ),
                "ListStatus": self._wrap(
                    lambda m: self.om.list_status(
                        m["volume"], m["bucket"], m["path"]
                    )
                ),
                # upgrade quiesce (`ozone admin om prepare` analog)
                "Prepare": self._wrap(
                    lambda m: {"txid": self.om.prepare()}),
                "CancelPrepare": self._wrap(
                    lambda m: self.om.cancel_prepare()),
                "PrepareStatus": self._wrap(
                    lambda m: {"prepared": self.om.prepared}),
                "SnapshotDiffSubmit": self._wrap(
                    lambda m: self.om.snapshot_diff_submit(
                        m["volume"], m["bucket"], m["from_snapshot"],
                        m.get("to_snapshot"))),
                "SnapshotDiffPage": self._wrap(
                    lambda m: self.om.snapshot_diff_page(
                        m["job_id"], m.get("token", ""),
                        m.get("page_size", 1000))),
                "SetBucketReplication": self._wrap(
                    lambda m: self.om.set_bucket_replication(
                        m["volume"], m["bucket"], m["replication"])),
                # tiny-object fast path (inline values + needle slabs;
                # deliberate extension — Apache Ozone 1.5 has neither)
                "SetBucketSmallObj": self._wrap(
                    lambda m: self.om.set_bucket_smallobj(
                        m["volume"], m["bucket"],
                        enabled=bool(m.get("enabled", True)),
                        inline_max=m.get("inline_max", 0),
                        needle_max=m.get("needle_max", 0))),
                "PutInlineKey": self._wrap(
                    lambda m: self.om.put_inline_key(
                        m["volume"], m["bucket"], m["key"],
                        base64.b64decode(m["data"]),
                        metadata=m.get("metadata"))),
                "CommitKeys": self._wrap(
                    lambda m: self.om.commit_keys(
                        m["volume"], m["bucket"], m["slab"],
                        m["entries"])),
                "SlabInfo": self._wrap(
                    lambda m: self.om.slab_info(
                        m["volume"], m["bucket"], m["slab_id"])),
                "ListSlabs": self._wrap(
                    lambda m: self.om.list_slabs(
                        m["volume"], m["bucket"])),
                "AllocateSlabGroup": self._allocate_slab_group,
                "ListOpenFiles": self._wrap(
                    lambda m: self.om.list_open_files(
                        m.get("volume", ""), m.get("bucket", ""),
                        m.get("prefix", ""), m.get("start_after", ""),
                        m.get("limit", 100))),
                # bucket lifecycle (tiering extension; no reference
                # analog — Apache Ozone 1.5 has no bucket lifecycle)
                "SetBucketLifecycle": self._wrap(
                    lambda m: self.om.set_bucket_lifecycle(
                        m["volume"], m["bucket"], m["rules"])),
                "GetBucketLifecycle": self._wrap(
                    lambda m: self.om.get_bucket_lifecycle(
                        m["volume"], m["bucket"])),
                "DeleteBucketLifecycle": self._wrap(
                    lambda m: self.om.delete_bucket_lifecycle(
                        m["volume"], m["bucket"])),
                "LifecycleStatus": self._wrap(
                    lambda m: self.om.lifecycle_status()),
                "LifecycleRunNow": self._wrap(
                    lambda m: self.om.run_lifecycle_once(
                        m.get("max_keys"))),
                "SlabCompactionRunNow": self._wrap(
                    lambda m: self.om.run_slab_compaction_once(
                        m.get("max_slabs"))),
                # cross-cluster bucket replication (geo-DR extension;
                # no reference analog — Apache Ozone 1.5 has no
                # bucket-level geo replication, PARITY row 47)
                "SetBucketGeoReplication": self._wrap(
                    lambda m: self.om.set_bucket_geo_replication(
                        m["volume"], m["bucket"], m["rules"])),
                "GetBucketGeoReplication": self._wrap(
                    lambda m: self.om.get_bucket_geo_replication(
                        m["volume"], m["bucket"])),
                "DeleteBucketGeoReplication": self._wrap(
                    lambda m: self.om.delete_bucket_geo_replication(
                        m["volume"], m["bucket"])),
                "GeoStatus": self._wrap(
                    lambda m: self.om.geo_status()),
                "GeoRunNow": self._wrap(
                    lambda m: self.om.run_geo_once(
                        m.get("max_entries"))),
                "GetDelegationToken": self._wrap(
                    lambda m: self.om.get_delegation_token(m["renewer"])),
                "RenewDelegationToken": self._wrap(
                    lambda m: self.om.renew_delegation_token(m["token"])),
                "CancelDelegationToken": self._wrap(
                    lambda m: self.om.cancel_delegation_token(m["token"])),
                # sharded metadata plane (om/sharding): the root map,
                # served by ANY replica — it is how a fresh client finds
                # the shard rings in the first place, so it cannot be
                # leader-gated
                "GetShardMap": self._wrap(
                    lambda m: self.om.store.get("system", "shard_map")),
        }
        server.add_service(
            SERVICE, {n: self._gated(n, fn) for n, fn in methods.items()},
            # bounded request queue (overload protection): past the
            # in-flight bound, calls are answered SERVER_BUSY instead of
            # piling up in the executor. GetShardMap stays exempt — it
            # is how a rejected client finds somewhere else to go.
            admission=admission.controller("om", exempt=self.UNGATED))

    #: verbs exempt from the HA leader gate (see GetShardMap above)
    UNGATED = frozenset({"GetShardMap"})

    def _gated(self, name: str, fn):
        def method(req: bytes) -> bytes:
            if self.gate is not None and name not in self.UNGATED:
                # verb-aware: the HA gate admits read verbs on followers
                # holding a live lease (om/sharding/leases.py) and
                # bounces everything else to the leader
                self.gate(name, req)
            return fn(req)

        return method

    def _identity(self, m: dict) -> tuple:
        """Caller identity for this request. A presented delegation token
        AUTHENTICATES the identity (verified signature + live server row,
        the reference's token-auth path); the plain _user/_groups fields
        are the trusted-transport identity assertion and are IGNORED when
        a token is present so a stolen field can't outrank a token. The
        third element records token-authentication so the OM can refuse
        GetDelegationToken to token-authenticated callers (a holder
        minting fresh tokens forever would defeat max_date)."""
        tok = m.pop("_dtoken", None)
        user = m.pop("_user", None)
        groups = m.pop("_groups", ())
        if tok is not None:
            row = self.om.verify_delegation_token(tok)  # raises OMError
            self._charge(row["owner"])
            return row["owner"], (), True
        self._charge(user)
        return user, groups, False

    def _charge(self, user) -> None:
        """Per-tenant admission at the OM front door: every
        identity-carrying verb books one op against the caller's bucket
        (OM work is metadata-shaped, so the ops dimension is the one
        that matters here). Raises StorageError(SERVER_BUSY) — carried
        to the client as a deterministic, hinted rejection."""
        ctl = admission.controller("om", exempt=self.UNGATED)
        if not (ctl.buckets.enabled or ctl.shedder.enabled):
            return
        tenant = user or "anonymous"
        ctl.charge(tenant, priority=admission.qos_class_for(tenant))

    def _wrap(self, fn, with_addresses: bool = False):
        def method(req: bytes) -> bytes:
            m, _ = wire.unpack(req)
            try:
                # bind the remote caller identity for ACL checks (the
                # reference carries UGI identity on every OM RPC)
                user, groups, via_token = self._identity(m)
                with self.om.user_context(user, groups,
                                          via_token=via_token):
                    out = fn(m)
            except OMError as e:
                raise StorageError(e.code, e.msg)
            resp = {"result": out}
            if self.applied_index_fn is not None:
                resp["_applied"] = self.applied_index_fn()
            if with_addresses:
                # located reads: the reference's OmKeyLocationInfo
                # carries DatanodeDetails for the key's pipelines only,
                # so a reader that never wrote (a gateway, a fresh
                # client) can resolve those nodes without a prior SCM
                # round trip — and a metadata-only lookup (dir marker,
                # zero block groups) stays O(1), not O(cluster)
                nodes = {n for g in (out or {}).get("block_groups", [])
                         for n in g.get("nodes", [])}
                if nodes:
                    book = self.addresses_provider()
                    resp["addresses"] = {
                        n: book[n] for n in nodes if n in book}
                    if self.locations_provider:
                        locs = self.locations_provider()
                        resp["locations"] = {
                            n: locs[n] for n in nodes if n in locs}
            return wire.pack(resp)

        return method

    def _open_key(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        try:
            user, groups, via_token = self._identity(m)
            with self.om.user_context(user, groups, via_token=via_token):
                s = self.om.open_key(
                    m["volume"], m["bucket"], m["key"],
                    m.get("replication"), metadata=m.get("metadata"),
                    acls=m.get("acls"),
                )
        except OMError as e:
            raise StorageError(e.code, e.msg)
        return wire.pack(
            {
                "client_id": s.client_id,
                "replication": str(s.replication),
                "checksum_type": s.checksum_type,
                "bytes_per_checksum": s.bytes_per_checksum,
                "block_size": self.om.block_size,
                # link buckets resolve server-side; the session must act
                # on the REAL names or its commit targets the alias
                "volume": s.volume,
                "bucket": s.bucket,
                # FSO sessions carry their tree position across the wire
                "parent_id": s.parent_id,
                "file_name": s.file_name,
                "encryption": s.encryption,
            }
        )

    def _allocate_block(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        g = self.om.grant_write_tokens(self.om.scm.allocate_block(
            ReplicationConfig.parse(m["replication"]),
            self.om.block_size,
            m.get("excluded"),
            m.get("excluded_containers"),
        ))
        if self.scm_barrier is not None:
            # HA: the allocation must survive leader failover before the
            # client writes data against it
            self.scm_barrier()
        return wire.pack(
            {"group": g.to_json(with_tokens=True),
             "addresses": self.addresses_provider(),
             "locations": (self.locations_provider()
                           if self.locations_provider else {})}
        )

    def _commit_multipart_part(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)

        class _S:
            volume = m["volume"]
            bucket = m["bucket"]
            key = m["key"]
            client_id = m["upload_id"]

        try:
            etag = self.om.commit_multipart_part(
                _S(), m["part_number"], self._groups_from(m["groups"]),
                m["size"], m["etag"], iv=m.get("iv", ""),
            )
        except OMError as e:
            raise StorageError(e.code, e.msg)
        return wire.pack({"result": etag})

    def _commit_key(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)

        class _S:  # minimal session view for commit
            volume = m["volume"]
            bucket = m["bucket"]
            key = m["key"]
            client_id = m["client_id"]
            replication = ReplicationConfig.parse(m["replication"])
            parent_id = m.get("parent_id")
            file_name = m.get("file_name")
            expect_object_id = m.get("expect_object_id", "")
            expect_generation = m.get("expect_generation", -1)

        try:
            self.om.commit_key(_S(), self._groups_from(m["groups"]), m["size"],
                               hsync=bool(m.get("hsync")))
        except OMError as e:
            raise StorageError(e.code, e.msg)
        resp = {}
        if self.applied_index_fn is not None:
            # the floor-advancing write on the freon put path
            resp["_applied"] = self.applied_index_fn()
        return wire.pack(resp)

    def _allocate_slab_group(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        g = self.om.allocate_slab_group(
            m["replication"], m.get("excluded"),
            m.get("excluded_containers"))
        if self.scm_barrier is not None:
            self.scm_barrier()
        return wire.pack(
            {"group": g.to_json(with_tokens=True),
             "addresses": self.addresses_provider(),
             "locations": (self.locations_provider()
                           if self.locations_provider else {})})

    def _recover_lease(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        try:
            out = self.om.recover_lease(m["volume"], m["bucket"], m["key"])
        except OMError as e:
            raise StorageError(e.code, e.msg)
        return wire.pack({"result": out})

    @staticmethod
    def _groups_from(groups: list[dict]) -> list[BlockGroup]:
        return [BlockGroup.from_json(g) for g in groups]


class RemoteOpenKeySession:
    def __init__(self, volume, bucket, key, meta):
        # the server reply carries link-resolved names when they differ
        self.volume = meta.get("volume", volume)
        self.bucket = meta.get("bucket", bucket)
        self.key = key
        self.client_id = meta["client_id"]
        self.replication = ReplicationConfig.parse(meta["replication"])
        self.checksum_type = meta["checksum_type"]
        self.bytes_per_checksum = meta["bytes_per_checksum"]
        self.parent_id = meta.get("parent_id")
        self.file_name = meta.get("file_name")
        self.encryption = meta.get("encryption", {})


class GrpcOmClient:
    """Remote OzoneManager with the attribute surface OzoneClient expects.

    `address` may be a comma-separated list of OM-HA replicas
    (OMFailoverProxyProvider analog): calls stick to the known leader,
    follow OM_NOT_LEADER hints, and rotate on connection failure."""

    def __init__(self, address: str, clients=None, tls=None, token=None,
                 shard_aware: Optional[bool] = None):
        from ozone_tpu.net.rpc import FailoverChannels

        self._pool = FailoverChannels(address, tls=tls)
        self.tls = tls  # downstream tools (freon scmtb) dial the SCM too
        self.addresses = self._pool.addresses
        self.address = self.addresses[0]
        self.block_size = 16 * 1024 * 1024
        self.clients = clients  # DatanodeClientFactory for address learning
        self._caller = threading.local()
        #: delegation token attached to every call — the authenticated
        #: identity path (jobs present the token instead of _user)
        self._token = token
        #: client-side shard routing (om/sharding/router.py): None =
        #: auto-discover via GetShardMap on first use (a deployment
        #: without a shard map costs one extra RPC, once), False =
        #: never route, True = require a routable map
        self._shard_aware = shard_aware
        self._router = None
        self._router_checked = shard_aware is False
        self._router_lock = threading.Lock()

    def use_token(self, token) -> None:
        self._token = token

    def user_context(self, user, groups=()):
        """Bind a caller identity to every RPC issued from this thread
        (mirrors OzoneManager.user_context; the identity rides the wire as
        _user/_groups fields)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prev = getattr(self._caller, "identity", None)
            self._caller.identity = (user, tuple(groups))
            try:
                yield
            finally:
                self._caller.identity = prev

        return _ctx()

    def _ensure_router(self) -> None:
        """One-shot shard-map discovery (see __init__)."""
        with self._router_lock:
            if self._router_checked:
                return
            self._router_checked = True
            try:
                mj = self._call("GetShardMap", _pool=self._pool)["result"]
            except StorageError:
                mj = None  # pre-sharding server or transient: stay flat
            if mj and any(mj.get("addresses", {}).values()):
                from ozone_tpu.om.sharding.router import ShardRouter

                self._router = ShardRouter(mj, tls=self.tls)
            elif self._shard_aware is True:
                raise StorageError(
                    "INVALID",
                    "shard_aware=True but the server has no routable "
                    "shard map")

    def _refresh_shard_map(self) -> None:
        """SHARD_MOVED invalidation: refetch the map, adopt it."""
        from ozone_tpu.om.sharding.router import METRICS

        METRICS.counter("moved_rejections").inc()
        mj = self._call("GetShardMap", _pool=self._pool)["result"]
        if mj and self._router is not None:
            self._router.update_map(mj)

    def _call(self, method: str, _pool=None, **meta) -> dict:
        from ozone_tpu.client import resilience
        from ozone_tpu.om.sharding.shardmap import SHARD_MOVED

        ident = getattr(self._caller, "identity", None)
        if ident is not None and ident[0] is not None:
            meta.setdefault("_user", ident[0])
            meta.setdefault("_groups", list(ident[1]))
        if self._token is not None:
            meta.setdefault("_dtoken", self._token)
        if _pool is None and not self._router_checked:
            self._ensure_router()
        # shard routing: bucket-addressed verbs go to the owning ring
        sid = None
        pool = _pool
        if pool is None and self._router is not None:
            sid, pool = self._router.route(method, meta)
        if pool is None:
            pool = self._pool
        # lease-based follower reads: spread read verbs over the shard's
        # replicas; a follower without a live lease (or behind the
        # caller's floor) bounces OM_NOT_LEADER and the retry below
        # falls back to the leader
        read_addr = None
        if sid is not None and "_min_applied" in meta:
            read_addr = self._router.read_address(sid)
        payload = wire.pack(meta)
        last: Exception | None = None
        attempts = max(4, 3 * len(self.addresses))
        # failover backoff: see resilience.failover_retry_policy — the
        # tuning (and its outlive-the-election rationale) lives there,
        # shared with the SCM client
        policy = resilience.failover_retry_policy(attempts)
        moved_retried = False
        for attempt in range(attempts):
            floor_s = None
            if read_addr is not None and attempt == 0:
                addr, ch = pool.channel(read_addr)
            else:
                addr, ch = pool.channel()
            try:
                m, _ = wire.unpack(ch.call(SERVICE, method, payload))
                if sid is None:
                    self.address = addr
                elif self._router is not None:
                    self._router.observe(sid, m)
                return m
            except StorageError as e:
                last = e
                if e.code == "OM_NOT_LEADER":
                    # msg carries the leader address when known
                    pool.follow_hint(e.msg)
                elif e.code == SHARD_MOVED and sid is not None \
                        and not moved_retried:
                    # stale shard map: the rejection is the cache
                    # invalidation — refetch, re-route, retry once
                    moved_retried = True
                    self._refresh_shard_map()
                    new_sid, new_pool = self._router.route(method, meta)
                    if new_pool is not None:
                        sid, pool = new_sid, new_pool
                        payload = wire.pack(meta)  # _min_applied moved
                elif e.code == "UNAVAILABLE":
                    # replica unreachable: drop its (possibly wedged)
                    # channel and rotate. Server-side errors
                    # (IO_EXCEPTION and application codes) surface —
                    # blind retry would re-execute non-idempotent writes
                    # and mask the real failure
                    pool.invalidate(addr)
                    if len(pool.addresses) == 1:
                        raise
                    pool.rotate()
                elif e.code == resilience.SERVER_BUSY:
                    # admission pushback from a HEALTHY peer: no
                    # invalidate, no rotation — back off (honoring the
                    # server's Retry-After hint as the floor) and retry
                    # the same replica. Rotating here would stampede the
                    # overload onto the next replica.
                    floor_s = resilience.server_pushback_floor(e, "om")
                else:
                    raise
            if not policy.sleep(attempt, floor_s=floor_s):
                # budget spent: surface fail-fast DEADLINE_EXCEEDED
                # instead of the transport-shaped error below
                resilience.check_deadline("om_failover")
                break
        if isinstance(last, StorageError) \
                and last.code == resilience.SERVER_BUSY:
            # retry budget spent while the server kept pushing back:
            # surface the pushback itself (the gateway maps it to 503
            # SlowDown), not a transport-shaped error that would trip
            # breakers on a healthy-but-loaded cluster
            raise last
        raise StorageError("IO_EXCEPTION",
                           f"no OM leader reachable: {last}")

    # namespace
    def create_volume(self, volume, owner="root"):
        if not self._router_checked:
            self._ensure_router()
        if self._router is not None:
            # volumes exist on EVERY shard (any shard may own buckets
            # of any volume) — fan the create out
            for pool in self._router.pools.values():
                self._call("CreateVolume", _pool=pool, volume=volume)
            return
        self._call("CreateVolume", volume=volume)

    def delete_volume(self, volume):
        if not self._router_checked:
            self._ensure_router()
        if self._router is not None:
            # check-all THEN delete-all: each shard's DeleteVolume only
            # sees its own buckets, so a one-pass delete could remove
            # the volume from empty shards and then fail
            for pool in self._router.pools.values():
                if self._call("ListBuckets", _pool=pool,
                              volume=volume)["result"]:
                    raise StorageError("VOLUME_NOT_EMPTY", volume)
            for pool in self._router.pools.values():
                self._call("DeleteVolume", _pool=pool, volume=volume)
            return
        self._call("DeleteVolume", volume=volume)

    def set_volume_owner(self, volume, owner):
        return self._call("SetVolumeOwner", volume=volume,
                          owner=owner)["result"]

    def volume_info(self, volume):
        return self._call("VolumeInfo", volume=volume)["result"]

    def list_volumes(self):
        return self._call("ListVolumes")["result"]

    def create_bucket(self, volume, bucket, replication="rs-6-3-1024k",
                      layout="OBJECT_STORE", encryption_key="",
                      gdpr=False):
        self._call("CreateBucket", volume=volume, bucket=bucket,
                   replication=replication, layout=layout,
                   encryption_key=encryption_key, gdpr=gdpr)

    # TDE / KMS (OzoneKMSUtil + KMSClientProvider surface)
    def kms_create_key(self, name, rotate=False):
        return self._call("KmsCreateKey", name=name,
                          rotate=rotate)["result"]

    def kms_key_info(self, name):
        return self._call("KmsKeyInfo", name=name)["result"]

    def kms_list_keys(self):
        return self._call("KmsListKeys")["result"]

    def kms_decrypt(self, volume, bucket, bundle):
        return self._call("KmsDecrypt", volume=volume, bucket=bucket,
                          bundle=bundle)["result"]

    def create_bucket_link(self, src_volume, src_bucket, volume, bucket):
        self._call("CreateBucketLink", src_volume=src_volume,
                   src_bucket=src_bucket, volume=volume, bucket=bucket)

    def delete_bucket(self, volume, bucket):
        self._call("DeleteBucket", volume=volume, bucket=bucket)

    def bucket_info(self, volume, bucket):
        return self._call("BucketInfo", volume=volume, bucket=bucket)["result"]

    def list_buckets(self, volume):
        if not self._router_checked:
            self._ensure_router()
        if self._router is not None:
            out = []
            for pool in self._router.pools.values():
                out.extend(self._call("ListBuckets", _pool=pool,
                                      volume=volume)["result"])
            return sorted(out, key=lambda b: b["name"])
        return self._call("ListBuckets", volume=volume)["result"]

    def get_shard_map(self):
        """The root shard map row, or None on unsharded deployments."""
        return self._call("GetShardMap")["result"]

    # keys
    def open_key(self, volume, bucket, key, replication=None,
                 metadata=None, acls=None):
        meta = self._call("OpenKey", volume=volume, bucket=bucket, key=key,
                          replication=replication, metadata=metadata,
                          acls=acls)
        self.block_size = meta.get("block_size", self.block_size)
        return RemoteOpenKeySession(volume, bucket, key, meta)

    def allocate_block(self, session, excluded: Optional[list[str]] = None,
                       excluded_containers=None):
        m = self._call(
            "AllocateBlock",
            replication=str(session.replication),
            excluded=excluded or [],
            excluded_containers=list(excluded_containers or ()),
        )
        self._learn_from(m)
        return BlockGroup.from_json(m["group"])

    def commit_key(self, session, groups, size, hsync=False):
        self._call(
            "CommitKey",
            volume=session.volume,
            bucket=session.bucket,
            key=session.key,
            client_id=session.client_id,
            replication=str(session.replication),
            groups=[g.to_json() for g in groups],
            size=size,
            parent_id=getattr(session, "parent_id", None),
            file_name=getattr(session, "file_name", None),
            hsync=hsync,
            expect_object_id=getattr(session, "expect_object_id", ""),
            expect_generation=getattr(session, "expect_generation", -1),
        )

    def hsync_key(self, session, groups, size):
        self.commit_key(session, groups, size, hsync=True)

    # small-object fast path (inline values + needle slabs). Values
    # ride the wire base64-encoded: the wire codec is string-keyed
    # JSON-shaped, and inline payloads are ≤ inline_max (~4 KiB) by
    # construction, so the 4/3 expansion is noise.
    def set_bucket_smallobj(self, volume, bucket, enabled=True,
                            inline_max=0, needle_max=0):
        return self._call("SetBucketSmallObj", volume=volume,
                          bucket=bucket, enabled=enabled,
                          inline_max=inline_max,
                          needle_max=needle_max)["result"]

    def smallobj_conf(self, binfo):
        from ozone_tpu.client.slab import smallobj_conf

        return smallobj_conf(binfo)

    def put_inline_key(self, volume, bucket, key, data, metadata=None):
        return self._call(
            "PutInlineKey", volume=volume, bucket=bucket, key=key,
            data=base64.b64encode(bytes(data)).decode("ascii"),
            metadata=metadata)["result"]

    def commit_keys(self, volume, bucket, slab, entries):
        return self._call("CommitKeys", volume=volume, bucket=bucket,
                          slab=slab, entries=list(entries))["result"]

    def slab_info(self, volume, bucket, slab_id):
        return self._call("SlabInfo", volume=volume, bucket=bucket,
                          slab_id=slab_id)["result"]

    def list_slabs(self, volume, bucket):
        return self._call("ListSlabs", volume=volume,
                          bucket=bucket)["result"]

    def allocate_slab_group(self, replication, excluded=None,
                            excluded_containers=None):
        m = self._call(
            "AllocateSlabGroup", replication=str(replication),
            excluded=excluded or [],
            excluded_containers=list(excluded_containers or ()))
        self._learn_from(m)
        return BlockGroup.from_json(m["group"])

    def recover_lease(self, volume, bucket, key):
        return self._call("RecoverLease", volume=volume, bucket=bucket,
                          key=key)["result"]

    def set_quota(self, volume, bucket="", quota_bytes=None,
                  quota_namespace=None):
        return self._call("SetQuota", volume=volume, bucket=bucket,
                          quota_bytes=quota_bytes,
                          quota_namespace=quota_namespace)["result"]

    def repair_quota(self, volume):
        return self._call("RepairQuota", volume=volume)["result"]

    def create_snapshot(self, volume, bucket, name):
        return self._call("CreateSnapshot", volume=volume, bucket=bucket,
                          name=name)["result"]

    def list_snapshots(self, volume, bucket):
        return self._call("ListSnapshots", volume=volume,
                          bucket=bucket)["result"]

    def snapshot_info(self, volume, bucket, name):
        return self._call("SnapshotInfo", volume=volume, bucket=bucket,
                          name=name)["result"]

    def delete_snapshot(self, volume, bucket, name):
        self._call("DeleteSnapshot", volume=volume, bucket=bucket,
                   name=name)

    def rename_snapshot(self, volume, bucket, name, new_name):
        return self._call("RenameSnapshot", volume=volume, bucket=bucket,
                          name=name, new_name=new_name)["result"]

    def snapshot_diff(self, volume, bucket, from_snapshot,
                      to_snapshot=None):
        return self._call("SnapshotDiff", volume=volume, bucket=bucket,
                          from_snapshot=from_snapshot,
                          to_snapshot=to_snapshot)["result"]

    def snapshot_keys(self, volume, bucket, name):
        return self._call("SnapshotKeys", volume=volume, bucket=bucket,
                          name=name)["result"]

    def _learn_from(self, m: dict):
        """Adopt the address book riding a located response (lookups
        and allocations both carry the OmKeyLocationInfo
        DatanodeDetails analog) so this client can read keys it never
        wrote. Returns the response's result payload, if any."""
        if self.clients is not None:
            for dn_id, addr in m.get("addresses", {}).items():
                self.clients.update_remote(dn_id, addr)
            self.clients.learn_locations(m.get("locations", {}))
        return m.get("result")

    def snapshot_lookup_key(self, volume, bucket, name, key):
        return self._learn_from(self._call(
            "SnapshotLookupKey", volume=volume,
            bucket=bucket, name=name, key=key))

    def lookup_key(self, volume, bucket, key):
        return self._learn_from(self._call(
            "LookupKey", volume=volume, bucket=bucket, key=key))

    def key_block_groups(self, info):
        out = [BlockGroup.from_json(g) for g in info["block_groups"]]
        return out

    def list_keys(self, volume, bucket, prefix="", start_after="",
                  limit=None):
        return self._call("ListKeys", volume=volume, bucket=bucket,
                          prefix=prefix, start_after=start_after,
                          limit=limit)["result"]

    def delete_key(self, volume, bucket, key, expect_object_id=""):
        self._call("DeleteKey", volume=volume, bucket=bucket, key=key,
                   expect_object_id=expect_object_id)

    def rename_key(self, volume, bucket, key, new_key):
        self._call("RenameKey", volume=volume, bucket=bucket, key=key,
                   new_key=new_key)

    def set_key_attrs(self, volume, bucket, key, attrs, preconds=None):
        return self._call("SetKeyAttrs", volume=volume, bucket=bucket,
                          key=key, attrs=attrs,
                          preconds=preconds)["result"]

    def set_bucket_attrs(self, volume, bucket, attrs):
        return self._call("SetBucketAttrs", volume=volume,
                          bucket=bucket, attrs=attrs)["result"]

    def upgrade_status(self):
        return self._call("UpgradeStatus")["result"]

    # s3 secrets / acl
    def get_s3_secret(self, access_id, create=True):
        return self._call("GetS3Secret", access_id=access_id,
                          create=create)["result"]

    def revoke_s3_secret(self, access_id):
        self._call("RevokeS3Secret", access_id=access_id)

    def snapshot_diff_submit(self, volume, bucket, from_snapshot,
                             to_snapshot=None):
        return self._call("SnapshotDiffSubmit", volume=volume,
                          bucket=bucket, from_snapshot=from_snapshot,
                          to_snapshot=to_snapshot)["result"]

    def snapshot_diff_page(self, job_id, token="", page_size=1000):
        return self._call("SnapshotDiffPage", job_id=job_id, token=token,
                          page_size=page_size)["result"]

    def set_bucket_replication(self, volume, bucket, replication):
        return self._call("SetBucketReplication", volume=volume,
                          bucket=bucket, replication=replication)["result"]

    # bucket lifecycle (tiering extension)
    def set_bucket_lifecycle(self, volume, bucket, rules):
        return self._call("SetBucketLifecycle", volume=volume,
                          bucket=bucket, rules=rules)["result"]

    def get_bucket_lifecycle(self, volume, bucket):
        return self._call("GetBucketLifecycle", volume=volume,
                          bucket=bucket)["result"]

    def delete_bucket_lifecycle(self, volume, bucket):
        self._call("DeleteBucketLifecycle", volume=volume, bucket=bucket)

    def lifecycle_status(self):
        return self._call("LifecycleStatus")["result"]

    def run_lifecycle_once(self, max_keys=None):
        return self._call("LifecycleRunNow", max_keys=max_keys)["result"]

    def run_slab_compaction_once(self, max_slabs=None):
        return self._call("SlabCompactionRunNow",
                          max_slabs=max_slabs)["result"]

    # cross-cluster bucket replication (geo-DR extension)
    def set_bucket_geo_replication(self, volume, bucket, rules):
        return self._call("SetBucketGeoReplication", volume=volume,
                          bucket=bucket, rules=rules)["result"]

    def get_bucket_geo_replication(self, volume, bucket):
        return self._call("GetBucketGeoReplication", volume=volume,
                          bucket=bucket)["result"]

    def delete_bucket_geo_replication(self, volume, bucket):
        self._call("DeleteBucketGeoReplication", volume=volume,
                   bucket=bucket)

    def geo_status(self):
        return self._call("GeoStatus")["result"]

    def run_geo_once(self, max_entries=None):
        return self._call("GeoRunNow", max_entries=max_entries)["result"]

    def list_open_files(self, volume="", bucket="", prefix="",
                        start_after="", limit=100):
        return self._call("ListOpenFiles", volume=volume, bucket=bucket,
                          prefix=prefix, start_after=start_after,
                          limit=limit)["result"]

    # delegation tokens
    def get_delegation_token(self, renewer):
        return self._call("GetDelegationToken", renewer=renewer)["result"]

    def renew_delegation_token(self, token):
        return self._call("RenewDelegationToken", token=token)["result"]

    def cancel_delegation_token(self, token):
        self._call("CancelDelegationToken", token=token)

    def set_bucket_acl(self, volume, bucket, acl):
        self._call("SetBucketAcl", volume=volume, bucket=bucket, acl=acl)

    def get_bucket_acl(self, volume, bucket):
        return self._call("GetBucketAcl", volume=volume, bucket=bucket)[
            "result"
        ]

    # native acls / tenants
    def modify_acl(self, obj_type, volume, bucket="", path="", op="add",
                   acls=None):
        from ozone_tpu.om.acl import normalize_acls

        return self._call("ModifyAcl", obj_type=obj_type, volume=volume,
                          bucket=bucket, path=path, op=op,
                          acls=normalize_acls(acls))["result"]

    def check_access(self, volume, bucket, key, right, user=None,
                     groups=()):
        self._call("CheckAccess", volume=volume, bucket=bucket, key=key,
                   right=right if isinstance(right, str) else right.name,
                   user=user, groups=list(groups))

    def get_acls(self, obj_type, volume, bucket="", path=""):
        return self._call("GetAcls", obj_type=obj_type, volume=volume,
                          bucket=bucket, path=path)["result"]

    def create_tenant(self, tenant, volume="", owner="root"):
        self._call("CreateTenant", tenant=tenant, volume=volume, owner=owner)

    def delete_tenant(self, tenant):
        self._call("DeleteTenant", tenant=tenant)

    def list_tenants(self):
        return self._call("ListTenants")["result"]

    def tenant_assign_user(self, tenant, user, access_id=""):
        return self._call("TenantAssignUser", tenant=tenant, user=user,
                          access_id=access_id)["result"]

    def tenant_revoke_access(self, access_id):
        self._call("TenantRevokeAccess", access_id=access_id)

    def list_tenant_users(self, tenant):
        return self._call("ListTenantUsers", tenant=tenant)["result"]

    def tenant_for_access_id(self, access_id):
        return self._call("TenantForAccessId", access_id=access_id)["result"]

    # multipart upload
    def initiate_multipart_upload(self, volume, bucket, key,
                                  replication=None, metadata=None):
        return self._call(
            "InitiateMultipartUpload", volume=volume, bucket=bucket,
            key=key, replication=replication, metadata=metadata,
        )["result"]

    def multipart_info(self, volume, bucket, key, upload_id):
        return self._call(
            "MultipartInfo", volume=volume, bucket=bucket, key=key,
            upload_id=upload_id,
        )["result"]

    def open_multipart_part(self, volume, bucket, key, upload_id):
        info = self.multipart_info(volume, bucket, key, upload_id)
        return RemoteOpenKeySession(
            volume, bucket, key,
            {
                "client_id": upload_id,
                "replication": info["replication"],
                "checksum_type": info["checksum_type"],
                "bytes_per_checksum": info["bytes_per_checksum"],
                # MPU rows store the link-resolved names
                "volume": info["volume"],
                "bucket": info["bucket"],
                "encryption": info.get("encryption", {}),
            },
        )

    def commit_multipart_part(self, session, part_number, groups, size,
                              etag, iv=""):
        return self._call(
            "CommitMultipartPart",
            volume=session.volume,
            bucket=session.bucket,
            key=session.key,
            upload_id=session.client_id,
            part_number=part_number,
            groups=[g.to_json() for g in groups],
            size=size,
            etag=etag,
            iv=iv,
        )["result"]

    def complete_multipart_upload(self, volume, bucket, key, upload_id,
                                  parts):
        return self._call(
            "CompleteMultipartUpload", volume=volume, bucket=bucket,
            key=key, upload_id=upload_id, parts=parts,
        )["result"]

    def abort_multipart_upload(self, volume, bucket, key, upload_id):
        self._call("AbortMultipartUpload", volume=volume, bucket=bucket,
                   key=key, upload_id=upload_id)

    def list_parts(self, volume, bucket, key, upload_id):
        return self._call("ListParts", volume=volume, bucket=bucket,
                          key=key, upload_id=upload_id)["result"]

    def list_multipart_uploads(self, volume, bucket, prefix=""):
        return self._call("ListMultipartUploads", volume=volume,
                          bucket=bucket, prefix=prefix)["result"]

    # FSO file-system verbs
    def create_directory(self, volume, bucket, path):
        self._call("CreateDirectory", volume=volume, bucket=bucket, path=path)

    def delete_directory(self, volume, bucket, path, recursive=False):
        self._call("DeleteDirectory", volume=volume, bucket=bucket,
                   path=path, recursive=recursive)

    def get_file_status(self, volume, bucket, path):
        return self._call("GetFileStatus", volume=volume, bucket=bucket,
                          path=path)["result"]

    def list_status(self, volume, bucket, path):
        return self._call("ListStatus", volume=volume, bucket=bucket,
                          path=path)["result"]

    def prepare(self):
        return self._call("Prepare")["result"]

    def cancel_prepare(self):
        self._call("CancelPrepare")

    def prepare_status(self):
        return self._call("PrepareStatus")["result"]

    def close(self):
        if self._router is not None:
            self._router.close()
        self._pool.close()
