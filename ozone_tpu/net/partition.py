"""Client-side network-partition injection — the blockade analog.

The reference tests network partitions with blockade/iptables around
docker containers (fault-injection-test/network-tests/src/test/blockade/
test_blockade_*.py: datanode isolation, SCM isolation, flaky net). This
framework's daemons all speak gRPC through RpcChannel, so a partition is
injected one layer up: every outbound call consults a process-global deny
table and fails with the same UNAVAILABLE StorageError a dead TCP peer
would produce — failover clients rotate, raft peers mark the target
unreachable and retry on the next heartbeat, exactly as if the wire were
cut.

Entries are scoped: ("*", dst) drops every call this process makes to
dst; (owner, dst) drops only calls made through channels tagged with that
owner — which is how an in-process HA minicluster isolates ONE replica of
a ring whose members all share the process (each replica's raft transport
tags its channels with its node id).

Real daemon processes expose Partition/Heal/PartitionList verbs on their
insight RPC service, so a test (or operator drill) can cut links between
live daemons remotely: cutting both directions of a link means one
Partition call to each endpoint's process, mirroring how blockade
programs netfilter in each container.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_blocked: set[tuple[str, str]] = set()
_delayed: dict[tuple[str, str], float] = {}

#: wildcard owner: matches calls from every channel in the process
ANY = "*"


def block(dst: str, owner: str = ANY) -> None:
    """Drop future calls to dst (from `owner`-tagged channels only, or
    from the whole process with the default wildcard)."""
    with _lock:
        _blocked.add((owner, dst))


def delay(dst: str, seconds: float, owner: str = ANY) -> None:
    """Add fixed latency to future calls to dst (the blockade slow/flaky
    network scenario: the link works, slowly)."""
    with _lock:
        _delayed[(owner, dst)] = float(seconds)


def heal(dst: str, owner: str = ANY) -> None:
    with _lock:
        _blocked.discard((owner, dst))
        _delayed.pop((owner, dst), None)


def clear() -> None:
    with _lock:
        _blocked.clear()
        _delayed.clear()


def blocked() -> list[tuple[str, str]]:
    with _lock:
        return sorted(_blocked)


def delayed() -> list[tuple[str, str, float]]:
    with _lock:
        return sorted((o, d, sec) for (o, d), sec in _delayed.items())


def delay_for(dst: str, owner: str | None = None) -> float:
    with _lock:
        if not _delayed:
            return 0.0
        d = _delayed.get((ANY, dst), 0.0)
        if owner is not None:
            d = max(d, _delayed.get((owner, dst), 0.0))
        return d


def is_blocked(dst: str, owner: str | None = None) -> bool:
    with _lock:
        if not _blocked:  # fast path: injection is a test/drill feature
            return False
        if (ANY, dst) in _blocked:
            return True
        return owner is not None and (owner, dst) in _blocked
