"""Client-side network-partition injection — the blockade analog.

The reference tests network partitions with blockade/iptables around
docker containers (fault-injection-test/network-tests/src/test/blockade/
test_blockade_*.py: datanode isolation, SCM isolation, flaky net). This
framework's daemons all speak gRPC through RpcChannel, so a partition is
injected one layer up: every outbound call consults a process-global deny
table and fails with the same UNAVAILABLE StorageError a dead TCP peer
would produce — failover clients rotate, raft peers mark the target
unreachable and retry on the next heartbeat, exactly as if the wire were
cut.

Entries are scoped: ("*", dst) drops every call this process makes to
dst; (owner, dst) drops only calls made through channels tagged with that
owner — which is how an in-process HA minicluster isolates ONE replica of
a ring whose members all share the process (each replica's raft transport
tags its channels with its node id).

Real daemon processes expose Partition/Heal/PartitionList verbs on their
insight RPC service, so a test (or operator drill) can cut links between
live daemons remotely: cutting both directions of a link means one
Partition call to each endpoint's process, mirroring how blockade
programs netfilter in each container.

Round 5 adds VERB-level rules (the byteman analog — the reference
injects latency/failures at method boundaries via dev-support/byteman/
*.btm scripts like ratis-no-flush.btm): a rule names (dst, verb, owner,
delay_s, drop_pct, count) and fires only on matching RPC methods, so a
slow-follower or drop-one-verb interleaving is reproducible without
LD_PRELOAD. `count`-limited rules with drop_pct=100 give fully
deterministic "fail the first N calls" semantics.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass, field
from typing import Optional

_lock = threading.Lock()
_blocked: set[tuple[str, str]] = set()
_delayed: dict[tuple[str, str], float] = {}

#: wildcard owner: matches calls from every channel in the process
ANY = "*"


@dataclass
class Rule:
    """One verb-scoped injection rule (byteman .btm analog)."""

    id: int
    dst: str = ANY  # peer address, or ANY
    verb: str = ANY  # RPC method name ("AppendEntries"), or ANY
    owner: str = ANY  # channel owner tag, or ANY
    delay_s: float = 0.0
    drop_pct: float = 0.0  # 0..100
    #: fire at most this many times, then auto-expire (None = forever);
    #: with drop_pct=100 this is the deterministic fail-first-N shape
    count: Optional[int] = None
    _rng: random.Random = field(default_factory=lambda: random.Random(7))

    def matches(self, dst: str, verb: Optional[str],
                owner: Optional[str]) -> bool:
        if self.dst != ANY and self.dst != dst:
            return False
        if self.verb != ANY:
            if verb is None:
                return False
            name = verb.rsplit("/", 1)[-1]
            if name != self.verb:
                return False
        if self.owner != ANY and self.owner != owner:
            return False
        return True


_rules: dict[int, Rule] = {}
_next_rule_id = [1]


def add_rule(dst: str = ANY, verb: str = ANY, owner: str = ANY,
             delay_s: float = 0.0, drop_pct: float = 0.0,
             count: Optional[int] = None, seed: int = 7) -> int:
    """Install a verb-scoped rule; returns its id for remove_rule."""
    with _lock:
        rid = _next_rule_id[0]
        _next_rule_id[0] += 1
        _rules[rid] = Rule(rid, dst, verb, owner, float(delay_s),
                           float(drop_pct), count,
                           random.Random(seed))
        return rid


def remove_rule(rule_id: int) -> None:
    with _lock:
        _rules.pop(rule_id, None)


def rules() -> list[dict]:
    with _lock:
        return [
            {"id": r.id, "dst": r.dst, "verb": r.verb, "owner": r.owner,
             "delay_s": r.delay_s, "drop_pct": r.drop_pct,
             "count": r.count}
            for r in _rules.values()
        ]


def consult(dst: str, verb: Optional[str] = None,
            owner: Optional[str] = None) -> tuple[bool, float]:
    """One-stop decision for an outbound call: (drop?, delay_seconds).
    Folds the legacy address tables with the verb rules; decrements
    count-limited rules as they fire."""
    with _lock:
        if not _blocked and not _delayed and not _rules:
            return False, 0.0
        if (ANY, dst) in _blocked or (
                owner is not None and (owner, dst) in _blocked):
            return True, 0.0
        d = _delayed.get((ANY, dst), 0.0)
        if owner is not None:
            d = max(d, _delayed.get((owner, dst), 0.0))
        drop = False
        expired = []
        for r in _rules.values():
            if not r.matches(dst, verb, owner):
                continue
            fired = False
            if r.drop_pct > 0 and (
                    r.drop_pct >= 100
                    or r._rng.uniform(0, 100) < r.drop_pct):
                drop = True
                fired = True
            if r.delay_s > 0:
                d = max(d, r.delay_s)
                fired = True
            if fired and r.count is not None:
                r.count -= 1
                if r.count <= 0:
                    expired.append(r.id)
        for rid in expired:
            _rules.pop(rid, None)
        return drop, d


def block(dst: str, owner: str = ANY) -> None:
    """Drop future calls to dst (from `owner`-tagged channels only, or
    from the whole process with the default wildcard)."""
    with _lock:
        _blocked.add((owner, dst))


def delay(dst: str, seconds: float, owner: str = ANY) -> None:
    """Add fixed latency to future calls to dst (the blockade slow/flaky
    network scenario: the link works, slowly)."""
    with _lock:
        _delayed[(owner, dst)] = float(seconds)


def heal(dst: str, owner: str = ANY) -> None:
    with _lock:
        _blocked.discard((owner, dst))
        _delayed.pop((owner, dst), None)


def clear() -> None:
    with _lock:
        _blocked.clear()
        _delayed.clear()


def blocked() -> list[tuple[str, str]]:
    with _lock:
        return sorted(_blocked)


def delayed() -> list[tuple[str, str, float]]:
    with _lock:
        return sorted((o, d, sec) for (o, d), sec in _delayed.items())


def delay_for(dst: str, owner: str | None = None) -> float:
    with _lock:
        if not _delayed:
            return 0.0
        d = _delayed.get((ANY, dst), 0.0)
        if owner is not None:
            d = max(d, _delayed.get((owner, dst), 0.0))
        return d


def is_blocked(dst: str, owner: str | None = None) -> bool:
    with _lock:
        if not _blocked:  # fast path: injection is a test/drill feature
            return False
        if (ANY, dst) in _blocked:
            return True
        return owner is not None and (owner, dst) in _blocked
