"""Raft messaging over the gRPC layer: many groups, one server.

Role analog of the reference's Ratis gRPC transport (Ratis carries Raft
RPCs between OMs — om/ratis/OzoneManagerRatisServer.java:108 —, SCMs
(server-scm ha/SCMRatisServerImpl), and datanode pipeline peers
(container-service XceiverServerRatis.java:124, one RaftServer hosting
one RaftGroup per pipeline)). One `RaftRpcService` on a process's
RpcServer serves every raft group that process participates in; requests
carry a group id and are routed to the registered `RaftNode`. The
`GrpcRaftTransport` is the consensus/raft.Transport implementation that
carries the same request/response dicts InProcessTransport passes
directly, so the raft core is byte-identical between test and daemon
deployments.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ozone_tpu.consensus.raft import RaftNode, Transport
from ozone_tpu.net import wire
from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.storage.ids import StorageError

log = logging.getLogger(__name__)

SERVICE = "raft"
_METHODS = ("request_vote", "append_entries", "install_snapshot",
            "fetch_state", "timeout_now")


class RaftRpcService:
    """Server side: routes raft RPCs to the group's local RaftNode."""

    def __init__(self, server: RpcServer):
        self._groups: dict[str, RaftNode] = {}
        self._lock = threading.Lock()
        server.add_service(SERVICE, {
            m: self._handler(m) for m in _METHODS
        })

    def register(self, group_id: str, node: RaftNode) -> None:
        with self._lock:
            self._groups[group_id] = node

    def unregister(self, group_id: str) -> None:
        with self._lock:
            self._groups.pop(group_id, None)

    def _handler(self, method: str):
        def handle(request: bytes) -> bytes:
            meta, _ = wire.unpack(request)
            gid = meta["group"]
            with self._lock:
                node = self._groups.get(gid)
            if node is None:
                raise StorageError("NO_SUCH_RAFT_GROUP",
                                   f"group {gid} not served here")
            resp = getattr(node, f"handle_{method}")(meta["req"])
            return wire.pack({"resp": resp})

        return handle


class GrpcRaftTransport(Transport):
    """Client side: one transport per (group, local node).

    `peers` maps peer node id -> "host:port" of the peer's RpcServer.
    Addresses may be learned late (a pipeline member may register before
    its peers are known) via `set_peer`.
    """

    def __init__(self, group_id: str, peers: dict[str, str],
                 tls=None, timeout_s: float = 5.0,
                 vote_timeout_s: float = 1.0,
                 owner: Optional[str] = None):
        self.group_id = group_id
        self._peers = dict(peers)
        self._tls = tls
        #: partition-injection scope tag for this node's outbound channels
        self._owner = owner
        self._timeout = timeout_s
        #: votes get a short deadline — a hung call to a dead peer inside
        #: an election round delays the candidate's next campaign
        self._vote_timeout = vote_timeout_s
        self._channels: dict[str, RpcChannel] = {}
        #: cert-rotation watermark (RotatingTls.version); retired
        #: channels are parked until close() — an immediate close could
        #: race an in-flight raft RPC
        self._tls_ver = getattr(tls, "version", None)
        self._retired: list[RpcChannel] = []
        self._lock = threading.Lock()

    def register(self, node: RaftNode) -> None:  # transport API, no-op
        pass

    def set_peer(self, peer_id: str, address: str) -> None:
        with self._lock:
            if self._peers.get(peer_id) != address:
                self._peers[peer_id] = address
                ch = self._channels.pop(peer_id, None)
                if ch is not None:
                    ch.close()

    def _channel(self, peer_id: str) -> RpcChannel:
        with self._lock:
            ver = getattr(self._tls, "version", None)
            if ver != self._tls_ver:
                # cert rotated: reconnect with the renewed identity
                self._retired.extend(self._channels.values())
                self._channels.clear()
                self._tls_ver = ver
            ch = self._channels.get(peer_id)
            if ch is None:
                addr = self._peers.get(peer_id)
                if addr is None:
                    raise ConnectionError(
                        f"no address for raft peer {peer_id}")
                ch = RpcChannel(addr, tls=self._tls,
                                server_name=peer_id if self._tls else None,
                                owner=self._owner)
                self._channels[peer_id] = ch
            return ch

    def send(self, peer_id: str, method: str, req: dict) -> dict:
        ch = self._channel(peer_id)
        timeout = (self._vote_timeout if method == "request_vote"
                   else self._timeout)
        try:
            raw = ch.call(SERVICE, method,
                          wire.pack({"group": self.group_id, "req": req}),
                          timeout=timeout)
        except StorageError as e:
            # the raft core treats any raised error as "peer unreachable"
            # and retries on the next heartbeat
            raise ConnectionError(str(e)) from e
        meta, _ = wire.unpack(raw)
        return meta["resp"]

    def close(self) -> None:
        with self._lock:
            chans = list(self._channels.values()) + self._retired
            self._channels.clear()
            self._retired = []
        for ch in chans:
            ch.close()
