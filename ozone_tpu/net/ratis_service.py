"""Client-facing RPC surface of the datanode Raft pipeline server.

The reference exposes Ratis writes through the same Xceiver protocol as
reads (XceiverClientRatis.sendRequestAsync:249 routes container commands
into the pipeline's Raft ring; watchForCommit:297 waits for all-replica
apply). Here the surface is three verbs on the datanode's RpcServer:
Submit (ordered write through the local leader), Watch (commit watermark
wait), Info (leadership/groups probe for client-side leader discovery).
"""

from __future__ import annotations

import logging
from typing import Optional, Protocol

from ozone_tpu.net import wire
from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.storage.ratis import RatisXceiverServer

log = logging.getLogger(__name__)

SERVICE = "xceiver-ratis"


class RatisGrpcService:
    def __init__(self, xceiver: RatisXceiverServer, server: RpcServer,
                 verifier=None):
        self.xceiver = xceiver
        #: shared with DatanodeGrpcService: the reference's
        #: ContainerStateMachine routes proposals through the same
        #: HddsDispatcher token check as direct gRPC ops
        self.verifier = verifier
        server.add_service(SERVICE, {
            "Submit": self._submit,
            "Watch": self._watch,
            "Info": self._info,
        })

    def _authorize(self, req: dict) -> None:
        """Token-gate a pipeline proposal at the leader (followers apply
        the committed log without re-checking, like the reference)."""
        if self.verifier is None or not self.verifier.enabled:
            return
        from ozone_tpu.storage.ids import (
            BLOCK_TOKEN_VERIFICATION_FAILED,
            BlockID,
            StorageError,
        )
        from ozone_tpu.utils.security import AccessMode, TokenError

        verb = req.get("verb")
        try:
            if verb in ("create_container", "close_container"):
                self.verifier.verify_container(
                    req.get("container_token"), int(req["container_id"]))
            elif verb == "write_chunk_commit":
                self.verifier.verify(
                    req.get("token"), BlockID.from_json(req["block_id"]),
                    AccessMode.WRITE)
            elif verb == "put_block":
                self.verifier.verify(
                    req.get("token"),
                    BlockID.from_json(req["block"]["block_id"]),
                    AccessMode.WRITE)
        except TokenError as e:
            raise StorageError(BLOCK_TOKEN_VERIFICATION_FAILED, str(e))

    def _submit(self, request: bytes) -> bytes:
        meta, _ = wire.unpack(request)
        self._authorize(meta.get("request") or {})
        out = self.xceiver.submit(int(meta["pipeline_id"]), meta["request"],
                                  timeout=float(meta.get("timeout", 30.0)))
        return wire.pack(out)

    def _watch(self, request: bytes) -> bytes:
        meta, _ = wire.unpack(request)
        out = self.xceiver.watch(
            int(meta["pipeline_id"]), int(meta["index"]),
            policy=meta.get("policy", "ALL"),
            timeout=float(meta.get("timeout", 30.0)),
        )
        return wire.pack(out)

    def _info(self, request: bytes) -> bytes:
        meta, _ = wire.unpack(request)
        pid = meta.get("pipeline_id")
        return wire.pack({
            "pipelines": self.xceiver.pipelines(),
            "leader": (self.xceiver.leader_of(int(pid))
                       if pid is not None else None),
        })


class RatisClient(Protocol):
    dn_id: str

    def submit(self, pipeline_id: int, request: dict,
               timeout: float = 30.0) -> dict: ...
    def watch(self, pipeline_id: int, index: int, policy: str = "ALL",
              timeout: float = 30.0) -> dict: ...
    def info(self, pipeline_id: Optional[int] = None) -> dict: ...


class LocalRatisClient:
    """In-process client over a RatisXceiverServer (tests/minicluster)."""

    def __init__(self, xceiver: RatisXceiverServer, dn_id: str):
        self.xceiver = xceiver
        self.dn_id = dn_id

    def submit(self, pipeline_id, request, timeout=30.0):
        return self.xceiver.submit(pipeline_id, request, timeout=timeout)

    def watch(self, pipeline_id, index, policy="ALL", timeout=30.0):
        return self.xceiver.watch(pipeline_id, index, policy=policy,
                                  timeout=timeout)

    def info(self, pipeline_id=None):
        return {
            "pipelines": self.xceiver.pipelines(),
            "leader": (self.xceiver.leader_of(pipeline_id)
                       if pipeline_id is not None else None),
        }


class GrpcRatisClient:
    def __init__(self, dn_id: str, address: str, tls=None):
        self.dn_id = dn_id
        self._ch = RpcChannel(address, tls=tls)

    def submit(self, pipeline_id, request, timeout=30.0):
        raw = self._ch.call(SERVICE, "Submit", wire.pack({
            "pipeline_id": pipeline_id, "request": request,
            "timeout": timeout,
        }), timeout=timeout + 5)
        return wire.unpack(raw)[0]

    def watch(self, pipeline_id, index, policy="ALL", timeout=30.0):
        raw = self._ch.call(SERVICE, "Watch", wire.pack({
            "pipeline_id": pipeline_id, "index": index, "policy": policy,
            "timeout": timeout,
        }), timeout=timeout + 5)
        return wire.unpack(raw)[0]

    def info(self, pipeline_id=None):
        raw = self._ch.call(SERVICE, "Info",
                            wire.pack({"pipeline_id": pipeline_id}))
        return wire.unpack(raw)[0]

    def close(self) -> None:
        self._ch.close()


class RatisClientFactory:
    """dn_id -> RatisClient resolver, local-first like
    client/dn_client.DatanodeClientFactory."""

    def __init__(self, address_source=None):
        self._local: dict[str, LocalRatisClient] = {}
        self._remote_addr: dict[str, str] = {}
        self._remote: dict[str, GrpcRatisClient] = {}
        self.tls = None
        #: cert-rotation watermark; retired clients are parked until
        #: close() so in-flight RPCs finish on the old channel
        self._tls_ver = None
        self._retired: list[GrpcRatisClient] = []
        #: optional dn_id -> address resolver (typically the datapath
        #: DatanodeClientFactory.remote_address — both services ride the
        #: same RpcServer, so one address book serves both)
        self._address_source = address_source

    def register_local(self, xceiver: RatisXceiverServer,
                       dn_id: str) -> LocalRatisClient:
        c = LocalRatisClient(xceiver, dn_id)
        self._local[dn_id] = c
        return c

    def register_remote(self, dn_id: str, address: str) -> None:
        if self._remote_addr.get(dn_id) != address:
            self._remote_addr[dn_id] = address
            old = self._remote.pop(dn_id, None)
            if old is not None:
                old.close()

    def maybe_get(self, dn_id: str) -> Optional[RatisClient]:
        c = self._local.get(dn_id)
        if c is not None:
            return c
        ver = getattr(self.tls, "version", None)
        if ver != self._tls_ver:
            # cert rotated: reconnect with the renewed identity
            self._retired.extend(self._remote.values())
            self._remote.clear()
            self._tls_ver = ver
        if self._address_source is not None:
            # re-resolve every time: a restarted datanode binds a new
            # port and the shared address book is refreshed by the OM
            fresh = self._address_source(dn_id)
            if fresh:
                self.register_remote(dn_id, fresh)
        c = self._remote.get(dn_id)
        if c is not None:
            return c
        addr = self._remote_addr.get(dn_id)
        if addr is None:
            return None
        c = GrpcRatisClient(dn_id, addr, tls=self.tls)
        self._remote[dn_id] = c
        return c

    def close(self) -> None:
        clients = list(self._remote.values()) + self._retired
        self._remote.clear()
        self._retired = []
        for c in clients:
            c.close()

    def get(self, dn_id: str) -> RatisClient:
        c = self.maybe_get(dn_id)
        if c is None:
            raise KeyError(f"no ratis client for {dn_id}")
        return c
