"""Generic gRPC plumbing: byte-level services without codegen.

Role analog of the reference's gRPC/Netty datapath transport
(XceiverServerGrpc.java:76 / GrpcXceiverService.java:42 on the server,
XceiverClientGrpc on the client). Services register python callables per
method name; requests/responses are raw bytes in the net/wire.py format.
Errors cross the wire as grpc ABORTED with a JSON {code, message} detail
and are re-raised as StorageError on the client.
"""

from __future__ import annotations

import contextlib
import json
import logging
import threading
from concurrent import futures
from typing import Callable, Optional

import grpc

from ozone_tpu.storage.ids import StorageError

log = logging.getLogger(__name__)

#: pem -> serial parse cache for revocation checks (bounded)
_SERIAL_CACHE: dict = {}

Method = Callable[[bytes], bytes]


#: stream-unary handler: consumes an iterator of request frames, returns
#: one response (the streaming-write commit ack shape)
StreamMethod = Callable[..., bytes]


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, methods: dict[str, Method],
                 stream_methods: Optional[dict[str, StreamMethod]] = None,
                 server_stream_methods: Optional[dict[str, Method]] = None,
                 server: Optional["RpcServer"] = None,
                 admission=None):
        self._methods = methods
        self._stream_methods = stream_methods or {}
        #: unary request -> iterator of byte frames (the replication
        #: download shape: large payloads never buffer in one message)
        self._server_stream_methods = server_stream_methods or {}
        #: owning server: read at call time for its live crl_provider
        self._server = server
        #: AdmissionController bounding this service's in-flight work:
        #: past the bound, new calls are answered SERVER_BUSY instead
        #: of queuing invisibly in the executor's backlog
        self._admission = admission

    @contextlib.contextmanager
    def _admit(self, method_name: str):
        ctl = self._admission
        if ctl is None:
            yield
            return
        with ctl.admit(method_name.rpartition("/")[2]):
            yield

    def _check_revoked(self, context) -> None:
        """Certificate revocation (the CRL the reference distributes
        from the SCM CA): a peer presenting a revoked-but-unexpired
        cert is refused at the application layer — the TLS handshake
        itself cannot consult a live CRL. Aborts UNAUTHENTICATED."""
        srv = self._server
        provider = getattr(srv, "crl_provider", None) if srv else None
        if provider is None:
            return
        crl = provider()
        if not crl:
            return
        pems = dict(context.auth_context()).get("x509_pem_cert") or []
        if not pems:
            return
        pem = bytes(pems[0])
        serial = _SERIAL_CACHE.get(pem)
        if serial is None:
            from cryptography import x509 as _x509

            serial = _x509.load_pem_x509_certificate(pem).serial_number
            if len(_SERIAL_CACHE) > 256:
                _SERIAL_CACHE.clear()
            _SERIAL_CACHE[pem] = serial
        if serial in crl:
            context.abort(
                grpc.StatusCode.UNAUTHENTICATED,
                json.dumps({"code": "CERTIFICATE_REVOKED",
                            "message": f"serial {serial} is revoked"}))

    def _guard(self, fn, method_name):
        def wrapped(request, context: grpc.ServicerContext) -> bytes:
            # before the try: context.abort raises to terminate, and
            # the generic except below must not re-wrap it as INTERNAL
            self._check_revoked(context)
            from ozone_tpu.utils.tracing import Tracer

            remote_ctx = dict(context.invocation_metadata()).get("x-trace-id")
            try:
                with self._admit(method_name), Tracer.instance().span(
                    f"server:{method_name}",
                    child_of=remote_ctx or None,
                ):
                    return fn(request)
            except StorageError as e:
                context.abort(
                    grpc.StatusCode.ABORTED,
                    json.dumps({"code": e.code, "message": e.msg}),
                )
            except Exception as e:  # noqa: BLE001 - surface as INTERNAL
                log.exception("rpc %s failed", method_name)
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    json.dumps({"code": "IO_EXCEPTION", "message": str(e)}),
                )

        return wrapped

    def _guard_stream(self, fn, method_name):
        """Guard for server-streaming handlers: exceptions fire during
        ITERATION of the response generator, so the try must wrap the
        yield loop, not just the call."""
        def wrapped(request, context: grpc.ServicerContext):
            self._check_revoked(context)
            from ozone_tpu.utils.tracing import Tracer

            remote_ctx = dict(context.invocation_metadata()).get(
                "x-trace-id")
            try:
                with self._admit(method_name), Tracer.instance().span(
                    f"server:{method_name}",
                    child_of=remote_ctx or None,
                ):
                    yield from fn(request)
            except StorageError as e:
                context.abort(
                    grpc.StatusCode.ABORTED,
                    json.dumps({"code": e.code, "message": e.msg}),
                )
            except Exception as e:  # noqa: BLE001 - surface as INTERNAL
                log.exception("rpc %s failed", method_name)
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    json.dumps({"code": "IO_EXCEPTION", "message": str(e)}),
                )

        return wrapped

    def service(self, handler_call_details):
        name = handler_call_details.method
        fn = self._methods.get(name)
        if fn is not None:
            return grpc.unary_unary_rpc_method_handler(self._guard(fn, name))
        sfn = self._stream_methods.get(name)
        if sfn is not None:
            return grpc.stream_unary_rpc_method_handler(self._guard(sfn, name))
        ssfn = self._server_stream_methods.get(name)
        if ssfn is not None:
            return grpc.unary_stream_rpc_method_handler(
                self._guard_stream(ssfn, name))
        return None


class RpcServer:
    """One grpc.Server hosting any number of named services.

    Pass `tls` (utils/ca.py TlsMaterial) to serve over TLS with client
    certificates required (the reference's SecurityConfig-driven
    grpc.tls.enabled mode on XceiverServerGrpc/ReplicationServer);
    `mutual=False` downgrades to server-auth-only TLS."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, tls=None, mutual: bool = True):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 128 * 1024 * 1024),
                ("grpc.max_receive_message_length", 128 * 1024 * 1024),
                # no SO_REUSEPORT: a restarted daemon re-binding its port
                # must either own it exclusively or fail — with reuseport
                # the kernel load-balances new connections onto the old
                # shutting-down server's socket, which accepts TCP but
                # never answers the HTTP/2 handshake
                ("grpc.so_reuseport", 0),
            ],
        )
        if tls is not None:
            self.port = self._server.add_secure_port(
                f"{host}:{port}", tls.server_credentials(mutual=mutual))
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self.tls_enabled = tls is not None
        #: callable() -> set of revoked cert serials (CRL); None = no
        #: revocation checking. Read per-request so updates apply live.
        self.crl_provider = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def add_service(self, service_name: str, methods: dict[str, Method],
                    stream_methods: Optional[dict[str, StreamMethod]] = None,
                    server_stream_methods: Optional[dict] = None,
                    admission=None) -> None:
        full = {
            f"/{service_name}/{name}": fn for name, fn in methods.items()
        }
        sfull = {
            f"/{service_name}/{name}": fn
            for name, fn in (stream_methods or {}).items()
        }
        ssfull = {
            f"/{service_name}/{name}": fn
            for name, fn in (server_stream_methods or {}).items()
        }
        self._server.add_generic_rpc_handlers(
            (_GenericHandler(full, sfull, ssfull, server=self,
                             admission=admission),))

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: Optional[float] = 0.5) -> None:
        # wait for full termination so the port is actually released
        # before a successor binds it
        self._server.stop(grace).wait(timeout=(grace or 0) + 5)


class RpcChannel:
    """Client side: method callables with raw-bytes serialization.

    `tls` (TlsMaterial) switches to a secure channel presenting this
    role's client certificate; `server_name` overrides SNI/authority when
    dialing by IP (certs carry role names + localhost SANs). `owner` tags
    the channel for scoped partition injection (net/partition.py)."""

    def __init__(self, address: str, tls=None,
                 server_name: Optional[str] = None,
                 owner: Optional[str] = None,
                 traced: bool = True):
        self.address = address
        self.owner = owner
        #: False for infrastructure channels (the span exporter) whose
        #: own RPCs must not generate spans — self-tracing feedback
        self.traced = traced
        options = [
            ("grpc.max_send_message_length", 128 * 1024 * 1024),
            ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            # bounded reconnect backoff: a channel dialed BEFORE its
            # server binds (launcher supervisors, HA rings booting,
            # fail-fast polls against a jax-importing daemon) must not
            # back off past the caller's whole readiness window — the
            # grpc default doubles toward 120 s, which made "poll until
            # the subprocess answers" loops miss servers that had been
            # up for a minute (the acceptance launcher flake)
            ("grpc.initial_reconnect_backoff_ms", 250),
            ("grpc.min_reconnect_backoff_ms", 250),
            ("grpc.max_reconnect_backoff_ms", 2000),
        ]
        if tls is not None:
            # daemons dial by IP:port while certs carry role + localhost
            # SANs; authentication is CA membership (mutual TLS), so the
            # default authority override targets the shared localhost SAN
            options.append((
                "grpc.ssl_target_name_override", server_name or "localhost"))
            self._channel = grpc.secure_channel(
                address, tls.channel_credentials(), options=options)
        else:
            self._channel = grpc.insecure_channel(address, options=options)
        self._calls: dict[str, Callable] = {}
        #: True once ANY call on this channel succeeded; a channel that
        #: never connected is the wedge-prone kind FailoverChannels
        #: .invalidate drops, while a once-healthy channel rides grpc's
        #: own reconnection through transient failures
        self.ever_connected = False

    def _map_rpc_error(self, key: str, e: grpc.RpcError):
        detail = e.details() or ""
        try:
            d = json.loads(detail)
            # a JSON-detail error was PRODUCED BY THE SERVER: the
            # connection works (a follower answering OM_NOT_LEADER
            # forever must not look "never connected" to
            # FailoverChannels.invalidate)
            self.ever_connected = True
            return StorageError(d.get("code", "IO_EXCEPTION"),
                                d.get("message", detail))
        except (ValueError, KeyError):
            # no JSON detail -> the server never produced an answer.
            # Transport-level failures get their own code so failover
            # clients can tell "replica unreachable: rotate" apart from
            # "server raised: surface it" (retrying a handler bug across
            # every replica would mask the real error)
            code = ("UNAVAILABLE"
                    if e.code() in (grpc.StatusCode.UNAVAILABLE,
                                    grpc.StatusCode.DEADLINE_EXCEEDED,
                                    grpc.StatusCode.CANCELLED)
                    else "IO_EXCEPTION")
            return StorageError(code,
                                f"rpc {key} to {self.address}: "
                                f"{e.code()}: {detail}")

    def _check_partition(self, key: str,
                         timeout: Optional[float] = None) -> None:
        from ozone_tpu.net import partition

        # one consult covers address partitions AND verb-level rules
        # (the byteman-analog method-boundary injection)
        drop, d = partition.consult(self.address, key, self.owner)
        if drop:
            raise StorageError(
                "UNAVAILABLE",
                f"rpc {key} to {self.address}: injected network partition",
            )
        if d > 0:
            import time as _time

            # injected link latency (slow-network drill) honors the
            # caller's deadline: latency past the timeout behaves like a
            # real slow link — block until the deadline, then fail
            if timeout is not None and d >= timeout:
                _time.sleep(timeout)  # ozlint: allow[deadline-propagation] -- injected chaos latency must block like a real slow link; bounded by the caller's timeout
                raise StorageError(
                    "UNAVAILABLE",
                    f"rpc {key} to {self.address}: injected latency "
                    f"{d}s exceeded deadline {timeout}s",
                )
            _time.sleep(d)  # ozlint: allow[deadline-propagation] -- injected chaos latency, not a retry sleep (partition.py delay rule)

    def call_streaming(self, service: str, method: str, frames,
                       timeout: Optional[float] = 120.0) -> bytes:
        """Client-streaming call: send an iterator of byte frames, get one
        response (the zero-round-trip-per-chunk write path)."""
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        self._check_partition(key, timeout)
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.stream_unary(key)
            self._calls[key] = fn
        tracer = Tracer.instance()
        try:
            with tracer.span(f"client:{key}", address=self.address):
                ctx = tracer.inject()
                metadata = (("x-trace-id", ctx),) if ctx else None
                return fn(iter(frames), timeout=timeout, metadata=metadata)
        except grpc.RpcError as e:
            raise self._map_rpc_error(key, e) from e

    def call_server_stream(self, service: str, method: str,
                           request: bytes,
                           timeout: Optional[float] = 300.0):
        """Server-streaming call: one request, an iterator of byte
        frames back (large downloads never buffer in one message)."""
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        self._check_partition(key, timeout)
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.unary_stream(key)
            self._calls[key] = fn
        tracer = Tracer.instance()
        try:
            with tracer.span(f"client:{key}", address=self.address):
                ctx = tracer.inject()
                metadata = (("x-trace-id", ctx),) if ctx else None
                yield from fn(request, timeout=timeout,
                              metadata=metadata)
        except grpc.RpcError as e:
            raise self._map_rpc_error(key, e) from e

    def call(self, service: str, method: str, request: bytes,
             timeout: Optional[float] = 30.0) -> bytes:
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        self._check_partition(key, timeout)
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.unary_unary(key)
            self._calls[key] = fn
        try:
            if not self.traced:
                out = fn(request, timeout=timeout)
            else:
                tracer = Tracer.instance()
                with tracer.span(f"client:{key}", address=self.address):
                    ctx = tracer.inject()
                    metadata = (("x-trace-id", ctx),) if ctx else None
                    out = fn(request, timeout=timeout, metadata=metadata)
            self.ever_connected = True
            return out
        except grpc.RpcError as e:
            raise self._map_rpc_error(key, e) from e

    def close(self) -> None:
        self._channel.close()


class FailoverChannels:
    """Address-list channel pool for HA failover clients (the
    OMFailoverProxyProvider / SCMBlockLocationFailoverProxyProvider
    plumbing): comma-list parsing, a thread-safe lazily-built channel
    cache, and a sticky index that follows leader hints or rotates on
    unreachable replicas. Shared by GrpcOmClient and GrpcScmClient so
    the failover behavior cannot drift between them."""

    def __init__(self, address: str, tls=None):
        self.addresses = [a.strip() for a in address.split(",")
                          if a.strip()]
        if not self.addresses:
            raise ValueError("empty address list")
        self._tls = tls
        self._chs: dict[str, RpcChannel] = {}
        #: channels to replicas retired by reconcile(); closed with the
        #: pool (an immediate close could race an in-flight RPC)
        self._retired: list[RpcChannel] = []
        #: cert-rotation watermark (RotatingTls.version): cached
        #: channels minted under a retired identity are dropped so the
        #: next call reconnects with the renewed cert
        self._tls_ver = getattr(tls, "version", None)
        self._idx = 0
        self._lock = threading.Lock()

    @property
    def current(self) -> str:
        with self._lock:
            return self.addresses[self._idx]

    def channel(self, addr: Optional[str] = None) -> tuple[str, RpcChannel]:
        with self._lock:
            ver = getattr(self._tls, "version", None)
            if ver != self._tls_ver:
                # the cert rotated: retire every cached channel (parked,
                # not closed — an in-flight RPC may still be using one)
                self._retired.extend(self._chs.values())
                self._chs.clear()
                self._tls_ver = ver
            a = addr if addr is not None else self.addresses[self._idx]
            ch = self._chs.get(a)
            if ch is None:
                ch = self._chs[a] = RpcChannel(a, tls=self._tls)
            return a, ch

    def rotate(self) -> None:
        with self._lock:
            self._idx = (self._idx + 1) % len(self.addresses)

    def invalidate(self, addr: str) -> None:
        """Drop AND close the cached channel for an UNREACHABLE
        replica: a channel dialed before its server ever bound can
        wedge in permanent TRANSIENT_FAILURE (fail-fast calls starving
        the subchannel's reconnect — observed against daemons whose jax
        import delays the bind by tens of seconds); recreating it on
        the next attempt reconnects instantly, which is what makes
        poll-until-up supervisor loops converge. Closing (not parking)
        is safe here: the channel is unreachable, so a concurrent
        in-flight RPC on it can only be waiting to fail — the close
        surfaces that as a clean rotate-and-retry, and parking one
        channel per poll tick would leak sockets for the whole wait.

        Only NEVER-connected channels are dropped: a once-healthy
        channel hitting a transient failure (a partition, a restart)
        recovers through grpc's own reconnection, and recreating it per
        failed call would churn sockets for the whole outage."""
        with self._lock:
            ch = self._chs.get(addr)
            if ch is None or ch.ever_connected:
                return
            del self._chs[addr]
        try:
            ch.close()
        except Exception:  # ozlint: allow[error-swallowing] -- best-effort channel teardown
            pass

    def reconcile(self, ring: list) -> None:
        """Adopt a server-shipped membership as the address list (online
        ring growth AND retirement: the server ships the full current
        ring on heartbeat responses, so clients both learn added
        replicas and stop dialing removed ones). The sticky index stays
        on the replica currently in use when it survives the change."""
        ring = [a.strip() for a in ring if a and a.strip()]
        if not ring:
            return
        with self._lock:
            if set(ring) == set(self.addresses):
                return
            cur = self.addresses[self._idx]
            # in place: callers alias this list (GrpcScmClient.addresses)
            self.addresses[:] = dict.fromkeys(ring)
            self._idx = (self.addresses.index(cur)
                         if cur in self.addresses else 0)
            # drop retired channels from the cache but DON'T close them
            # here: a concurrent caller may be mid-RPC on one, and a
            # forced close would surface a spurious error instead of a
            # clean rotate-and-retry. Ring changes are rare, so parking
            # them until close() is bounded in practice.
            self._retired.extend(self._chs.pop(a) for a in list(self._chs)
                                 if a not in self.addresses)

    def follow_hint(self, addr: Optional[str]) -> None:
        """Pin to a hinted leader address; a hint that is unknown or
        points back at the current replica rotates instead (a deposed
        leader advertising itself must not pin clients forever)."""
        with self._lock:
            if addr and addr in self.addresses:
                i = self.addresses.index(addr)
                if i != self._idx:
                    self._idx = i
                    return
            self._idx = (self._idx + 1) % len(self.addresses)

    def close(self) -> None:
        with self._lock:
            chans = list(self._chs.values()) + self._retired
            self._chs.clear()
            self._retired = []
        for ch in chans:
            ch.close()
