"""Generic gRPC plumbing: byte-level services without codegen.

Role analog of the reference's gRPC/Netty datapath transport
(XceiverServerGrpc.java:76 / GrpcXceiverService.java:42 on the server,
XceiverClientGrpc on the client). Services register python callables per
method name; requests/responses are raw bytes in the net/wire.py format.
Errors cross the wire as grpc ABORTED with a JSON {code, message} detail
and are re-raised as StorageError on the client.
"""

from __future__ import annotations

import json
import logging
from concurrent import futures
from typing import Callable, Optional

import grpc

from ozone_tpu.storage.ids import StorageError

log = logging.getLogger(__name__)

Method = Callable[[bytes], bytes]


#: stream-unary handler: consumes an iterator of request frames, returns
#: one response (the streaming-write commit ack shape)
StreamMethod = Callable[..., bytes]


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, methods: dict[str, Method],
                 stream_methods: Optional[dict[str, StreamMethod]] = None):
        self._methods = methods
        self._stream_methods = stream_methods or {}

    @staticmethod
    def _guard(fn, method_name):
        def wrapped(request, context: grpc.ServicerContext) -> bytes:
            from ozone_tpu.utils.tracing import Tracer

            remote_ctx = dict(context.invocation_metadata()).get("x-trace-id")
            try:
                with Tracer.instance().span(
                    f"server:{method_name}",
                    child_of=remote_ctx or None,
                ):
                    return fn(request)
            except StorageError as e:
                context.abort(
                    grpc.StatusCode.ABORTED,
                    json.dumps({"code": e.code, "message": e.msg}),
                )
            except Exception as e:  # noqa: BLE001 - surface as INTERNAL
                log.exception("rpc %s failed", method_name)
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    json.dumps({"code": "IO_EXCEPTION", "message": str(e)}),
                )

        return wrapped

    def service(self, handler_call_details):
        name = handler_call_details.method
        fn = self._methods.get(name)
        if fn is not None:
            return grpc.unary_unary_rpc_method_handler(self._guard(fn, name))
        sfn = self._stream_methods.get(name)
        if sfn is not None:
            return grpc.stream_unary_rpc_method_handler(self._guard(sfn, name))
        return None


class RpcServer:
    """One grpc.Server hosting any number of named services.

    Pass `tls` (utils/ca.py TlsMaterial) to serve over TLS with client
    certificates required (the reference's SecurityConfig-driven
    grpc.tls.enabled mode on XceiverServerGrpc/ReplicationServer);
    `mutual=False` downgrades to server-auth-only TLS."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, tls=None, mutual: bool = True):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 128 * 1024 * 1024),
                ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            ],
        )
        if tls is not None:
            self.port = self._server.add_secure_port(
                f"{host}:{port}", tls.server_credentials(mutual=mutual))
        else:
            self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self.tls_enabled = tls is not None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def add_service(self, service_name: str, methods: dict[str, Method],
                    stream_methods: Optional[dict[str, StreamMethod]] = None,
                    ) -> None:
        full = {
            f"/{service_name}/{name}": fn for name, fn in methods.items()
        }
        sfull = {
            f"/{service_name}/{name}": fn
            for name, fn in (stream_methods or {}).items()
        }
        self._server.add_generic_rpc_handlers(
            (_GenericHandler(full, sfull),))

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)


class RpcChannel:
    """Client side: method callables with raw-bytes serialization.

    `tls` (TlsMaterial) switches to a secure channel presenting this
    role's client certificate; `server_name` overrides SNI/authority when
    dialing by IP (certs carry role names + localhost SANs)."""

    def __init__(self, address: str, tls=None,
                 server_name: Optional[str] = None):
        self.address = address
        options = [
            ("grpc.max_send_message_length", 128 * 1024 * 1024),
            ("grpc.max_receive_message_length", 128 * 1024 * 1024),
        ]
        if tls is not None:
            if server_name:
                options.append((
                    "grpc.ssl_target_name_override", server_name))
            self._channel = grpc.secure_channel(
                address, tls.channel_credentials(), options=options)
        else:
            self._channel = grpc.insecure_channel(address, options=options)
        self._calls: dict[str, Callable] = {}

    def _map_rpc_error(self, key: str, e: grpc.RpcError):
        detail = e.details() or ""
        try:
            d = json.loads(detail)
            return StorageError(d.get("code", "IO_EXCEPTION"),
                                d.get("message", detail))
        except (ValueError, KeyError):
            return StorageError("IO_EXCEPTION",
                                f"rpc {key} to {self.address}: "
                                f"{e.code()}: {detail}")

    def call_streaming(self, service: str, method: str, frames,
                       timeout: Optional[float] = 120.0) -> bytes:
        """Client-streaming call: send an iterator of byte frames, get one
        response (the zero-round-trip-per-chunk write path)."""
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.stream_unary(key)
            self._calls[key] = fn
        tracer = Tracer.instance()
        try:
            with tracer.span(f"client:{key}", address=self.address):
                ctx = tracer.inject()
                metadata = (("x-trace-id", ctx),) if ctx else None
                return fn(iter(frames), timeout=timeout, metadata=metadata)
        except grpc.RpcError as e:
            raise self._map_rpc_error(key, e) from e

    def call(self, service: str, method: str, request: bytes,
             timeout: Optional[float] = 30.0) -> bytes:
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.unary_unary(key)
            self._calls[key] = fn
        tracer = Tracer.instance()
        try:
            with tracer.span(f"client:{key}", address=self.address):
                ctx = tracer.inject()
                metadata = (("x-trace-id", ctx),) if ctx else None
                return fn(request, timeout=timeout, metadata=metadata)
        except grpc.RpcError as e:
            raise self._map_rpc_error(key, e) from e

    def close(self) -> None:
        self._channel.close()
