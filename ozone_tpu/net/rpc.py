"""Generic gRPC plumbing: byte-level services without codegen.

Role analog of the reference's gRPC/Netty datapath transport
(XceiverServerGrpc.java:76 / GrpcXceiverService.java:42 on the server,
XceiverClientGrpc on the client). Services register python callables per
method name; requests/responses are raw bytes in the net/wire.py format.
Errors cross the wire as grpc ABORTED with a JSON {code, message} detail
and are re-raised as StorageError on the client.
"""

from __future__ import annotations

import json
import logging
from concurrent import futures
from typing import Callable, Optional

import grpc

from ozone_tpu.storage.ids import StorageError

log = logging.getLogger(__name__)

Method = Callable[[bytes], bytes]


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, methods: dict[str, Method]):
        self._methods = methods

    def service(self, handler_call_details):
        fn = self._methods.get(handler_call_details.method)
        if fn is None:
            return None

        def wrapped(request: bytes, context: grpc.ServicerContext) -> bytes:
            from ozone_tpu.utils.tracing import Tracer

            remote_ctx = dict(context.invocation_metadata()).get("x-trace-id")
            try:
                with Tracer.instance().span(
                    f"server:{handler_call_details.method}",
                    child_of=remote_ctx or None,
                ):
                    return fn(request)
            except StorageError as e:
                context.abort(
                    grpc.StatusCode.ABORTED,
                    json.dumps({"code": e.code, "message": e.msg}),
                )
            except Exception as e:  # noqa: BLE001 - surface as INTERNAL
                log.exception("rpc %s failed", handler_call_details.method)
                context.abort(
                    grpc.StatusCode.INTERNAL,
                    json.dumps({"code": "IO_EXCEPTION", "message": str(e)}),
                )

        return grpc.unary_unary_rpc_method_handler(wrapped)


class RpcServer:
    """One grpc.Server hosting any number of named services."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16):
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers),
            options=[
                ("grpc.max_send_message_length", 128 * 1024 * 1024),
                ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            ],
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def add_service(self, service_name: str, methods: dict[str, Method]) -> None:
        full = {
            f"/{service_name}/{name}": fn for name, fn in methods.items()
        }
        self._server.add_generic_rpc_handlers((_GenericHandler(full),))

    def start(self) -> None:
        self._server.start()

    def stop(self, grace: Optional[float] = 0.5) -> None:
        self._server.stop(grace)


class RpcChannel:
    """Client side: method callables with raw-bytes serialization."""

    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_send_message_length", 128 * 1024 * 1024),
                ("grpc.max_receive_message_length", 128 * 1024 * 1024),
            ],
        )
        self._calls: dict[str, Callable] = {}

    def call(self, service: str, method: str, request: bytes,
             timeout: Optional[float] = 30.0) -> bytes:
        from ozone_tpu.utils.tracing import Tracer

        key = f"/{service}/{method}"
        fn = self._calls.get(key)
        if fn is None:
            fn = self._channel.unary_unary(key)
            self._calls[key] = fn
        tracer = Tracer.instance()
        try:
            with tracer.span(f"client:{key}", address=self.address):
                ctx = tracer.inject()
                metadata = (("x-trace-id", ctx),) if ctx else None
                return fn(request, timeout=timeout, metadata=metadata)
        except grpc.RpcError as e:
            detail = e.details() or ""
            try:
                d = json.loads(detail)
                raise StorageError(d.get("code", "IO_EXCEPTION"),
                                   d.get("message", detail)) from e
            except (ValueError, KeyError):
                raise StorageError("IO_EXCEPTION",
                                   f"rpc {key} to {self.address}: "
                                   f"{e.code()}: {detail}") from e

    def close(self) -> None:
        self._channel.close()
