"""SCM gRPC service + remote client: registration, heartbeats, allocation.

Mirrors the reference's SCM protocol surface (ScmServerDatanodeHeartbeat
Protocol.proto for DN registration/heartbeat with piggybacked commands;
ScmServerProtocol block allocation used by the OM). Commands are
serialized with a type tag and the node address book, so remote datanodes
can execute reconstruction against peers they have never met.
"""

from __future__ import annotations

from dataclasses import asdict
from typing import Optional

from ozone_tpu import admission
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.net import wire
from ozone_tpu.net.rpc import RpcChannel, RpcServer
from ozone_tpu.scm.pipeline import ReplicationConfig
from ozone_tpu.scm.replication_manager import (
    DeleteReplicaCommand,
    ReplicateCommand,
)
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.storage.reconstruction import ReconstructionCommand

SERVICE = "ozone.tpu.ScmService"


def serialize_command(cmd, addresses: dict[str, str]) -> dict:
    if isinstance(cmd, ReconstructionCommand):
        return {
            "type": "reconstruct",
            "container_id": cmd.container_id,
            "replication": str(
                CoderOptions(
                    cmd.replication.data_units,
                    cmd.replication.parity_units,
                    cmd.replication.codec,
                    cmd.replication.cell_size,
                )
            ),
            "sources": {str(k): v for k, v in cmd.sources.items()},
            "targets": {str(k): v for k, v in cmd.targets.items()},
            "addresses": addresses,
        }
    from ozone_tpu.scm.block_deletion import DeleteBlocksCommand

    if isinstance(cmd, DeleteBlocksCommand):
        return {
            "type": "delete_blocks",
            "tx_ids": cmd.tx_ids,
            "blocks": [b.to_json() for b in cmd.blocks],
        }
    if isinstance(cmd, DeleteReplicaCommand):
        return {"type": "delete_replica", **asdict(cmd)}
    if isinstance(cmd, ReplicateCommand):
        return {"type": "replicate", **asdict(cmd), "addresses": addresses}
    if isinstance(cmd, dict):
        return cmd
    return {"type": "unknown", "repr": repr(cmd)}


def deserialize_command(d: dict):
    t = d.get("type")
    if t == "reconstruct":
        return ReconstructionCommand(
            container_id=d["container_id"],
            replication=CoderOptions.parse(d["replication"]),
            sources={int(k): v for k, v in d["sources"].items()},
            targets={int(k): v for k, v in d["targets"].items()},
        )
    if t == "delete_blocks":
        from ozone_tpu.scm.block_deletion import DeleteBlocksCommand
        from ozone_tpu.storage.ids import BlockID

        return DeleteBlocksCommand(
            list(d["tx_ids"]),
            [BlockID.from_json(b) for b in d["blocks"]],
        )
    if t == "delete_replica":
        return DeleteReplicaCommand(d["container_id"], d.get("replica_index", 0))
    if t == "replicate":
        return ReplicateCommand(
            d["container_id"], d["source"], d["target"],
            d.get("replica_index", 0),
        )
    return d


class ScmGrpcService:
    def __init__(self, scm: StorageContainerManager, server: RpcServer):
        self.scm = scm
        #: secret keys leave the SCM only over channels that
        #: authenticated the caller — mutual TLS on this server — or
        #: when the operator explicitly opted into insecure distribution
        #: (test clusters); otherwise ANY caller of Register/Heartbeat
        #: could mint its own tokens and the datapath enforcement would
        #: be decorative
        self.distribute_secrets = server.tls_enabled
        self.addresses: dict[str, str] = {}
        #: optional hook fired when a node (re)registers with a new
        #: address (daemon wires pipeline re-announcement through it)
        self.on_register = None
        #: HA hooks, set by the daemon. `gate` rejects state-mutating
        #: client calls on followers (registration/heartbeats stay open
        #: on every replica — the reference's datanodes heartbeat all
        #: SCMs); `barrier` blocks until the decision records a leader
        #: allocation produced are quorum-committed.
        self.gate = None
        self.barrier = None
        #: HA hook: replicates a mutating admin op through the metadata
        #: ring (callable(op, target) -> dict) so the decision survives
        #: leader failover; None = apply directly to the local SCM
        self.admin_submitter = None
        #: HA hook: ring membership changes (callable(op, target) ->
        #: members dict); None = not an HA deployment
        self.ring_ops = None
        #: HA hook: this replica's ring view (roles verb); any replica
        #: answers, so it is NOT leader-gated
        self.ring_status = None
        #: CA lifecycle hook (callable(op, target)); set by the daemon
        #: that hosts the cluster CA (cert-list / cert-revoke)
        self.cert_ops = None
        #: HA hook: current ring replica addresses, shipped on
        #: register/heartbeat responses so datanodes follow an online-
        #: grown ring without reconfiguration (a freshly added replica
        #: that never receives heartbeats would sit in safemode forever
        #: if it won an election)
        self.ring_provider = None
        server.add_service(
            SERVICE,
            {
                "Register": self._register,
                "Heartbeat": self._heartbeat,
                "AllocateBlock": self._allocate_block,
                "NodeAddresses": self._node_addresses,
                "Status": self._status,
                "ListContainers": self._list_containers,
                "AdminOp": self._admin_op,
            },
            # bounded request queue: client-facing verbs are refused
            # past the in-flight bound; node liveness traffic is exempt
            # — shedding heartbeats under load would convert overload
            # into a dead-node storm (re-replication on top of the
            # flood), the opposite of graceful degradation
            admission=admission.controller(
                "scm",
                exempt=frozenset({"Register", "Heartbeat",
                                  "NodeAddresses", "Status"})),
        )

    def _register(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        changed = self.addresses.get(m["dn_id"]) != m["address"]
        self.addresses[m["dn_id"]] = m["address"]
        self.scm.register_datanode(
            m["dn_id"], m.get("rack", "/default-rack"),
            m.get("capacity_bytes", 0),
            op_state=m.get("op_state"),
        )
        if changed and self.on_register is not None:
            # a restarted node binds a new port: peers holding the old
            # address (e.g. its pipelines' raft transports) are refreshed
            self.on_register(m["dn_id"])
        return wire.pack(self._security_fields())

    def _security_fields(self) -> dict:
        """Token secret-key distribution rides the register/heartbeat
        responses (the reference's SecretKeyProtocol served from the
        SCM): datanodes import the keys and turn on datapath token
        verification."""
        out = {}
        if self.ring_provider is not None:
            out["ring"] = list(self.ring_provider())
        if not getattr(self.scm, "block_tokens", False):
            return out
        out["block_tokens"] = True
        if self.distribute_secrets:
            out["secret_keys"] = self.scm.secret_keys.export_keys()
        return out

    def _heartbeat(self, req: bytes) -> bytes:
        m, _ = wire.unpack(req)
        cmds = self.scm.heartbeat(
            m["dn_id"],
            container_report=m.get("container_report"),
            used_bytes=m.get("used_bytes", 0),
            deleted_block_acks=m.get("deleted_block_acks"),
            layout_version=m.get("layout_version"),
            healthy_volumes=m.get("healthy_volumes"),
        )
        return wire.pack(
            {
                "commands": [
                    serialize_command(c, dict(self.addresses)) for c in cmds
                ],
                **self._security_fields(),
            }
        )

    def _allocate_block(self, req: bytes) -> bytes:
        if self.gate is not None:
            self.gate()  # follower-local allocation would never replicate
        m, _ = wire.unpack(req)
        g = self.scm.allocate_block(
            ReplicationConfig.parse(m["replication"]),
            m["block_size"],
            m.get("excluded"),
        )
        if self.barrier is not None:
            self.barrier()  # allocation must survive leader failover
        return wire.pack({"group": g.to_json(), "addresses": dict(self.addresses)})

    def node_locations(self) -> dict[str, str]:
        """dn_id -> topology location path (multi-level: "/dc/rack")."""
        return {n.dn_id: n.rack for n in self.scm.nodes.nodes()}

    def _node_addresses(self, req: bytes) -> bytes:
        return wire.pack({"addresses": dict(self.addresses),
                          "locations": self.node_locations()})

    #: admin verbs that change cluster state (leader-only under HA; the
    #: read-only ones may be answered by any replica)
    _MUTATING_ADMIN = frozenset({
        "decommission", "recommission", "maintenance",
        "balancer-start", "balancer-stop",
        "safemode-enter", "safemode-exit",
        "close-container", "close-pipeline", "finalize-upgrade",
    })

    def _admin_op(self, req: bytes) -> bytes:
        """Operator verbs (`ozone admin` analog: NodeDecommissionManager,
        ContainerBalancerCommands, SafeModeCommands, pipeline list)."""
        m, _ = wire.unpack(req)
        op, target = m["op"], m.get("target")
        scm = self.scm
        if op == "ring-status":
            # any replica answers (followers report the leader hint);
            # NOT leader-gated, unlike the membership mutations below
            if self.ring_status is None:
                raise StorageError("UNSUPPORTED_REQUEST",
                                   "not an HA deployment")
            return wire.pack(self.ring_status())
        if op in ("ring-add", "ring-remove", "ring-transfer"):
            # membership change IS its own replication (the config
            # entry rides the raft log), so it does not go through the
            # admin submitter; transfer likewise acts directly on the
            # leader's raft node
            if self.ring_ops is None:
                raise StorageError("UNSUPPORTED_REQUEST",
                                   "not an HA deployment")
            if self.gate is not None:
                self.gate()
            out = self.ring_ops(op, target)
            if op == "ring-transfer":
                return wire.pack(out)
            return wire.pack({"members": out})
        if op in ("cert-list", "cert-revoke"):
            # CA lifecycle ops: answered by the replica hosting the
            # root CA (daemon wires cert_ops when it owns one)
            if self.cert_ops is None:
                raise StorageError(
                    "UNSUPPORTED_REQUEST",
                    "this replica does not host the cluster CA")
            return wire.pack({"result": self.cert_ops(op, target)})
        if op in self._MUTATING_ADMIN:
            if self.gate is not None:
                self.gate()
            if self.admin_submitter is not None:
                out = self.admin_submitter(op, target)  # via the HA ring
            else:
                out = scm.apply_admin_op(op, target)
        elif op == "balancer-status":
            out = scm.balancer_status()
        elif op == "upgrade-status":
            # finalization progress (ozone admin scm finalizationstatus
            # analog): read-only view of the layout-feature catalog
            if scm.finalizer is not None:
                out = scm.finalizer.status()
            else:
                from ozone_tpu.utils.upgrade import FEATURES, LATEST_VERSION

                out = {"metadata_version": LATEST_VERSION,
                       "software_version": LATEST_VERSION,
                       "needs_finalization": False,
                       "features": [{"name": f.name, "version": f.version,
                                     "allowed": True} for f in FEATURES]}
        elif op in ("container-token", "block-token"):
            # operator token minting for dn-direct debug/repair tools
            # (SCMSecurityProtocol.getContainerToken analog); no-op on
            # insecure clusters so tools need no mode switch
            if not getattr(scm, "block_tokens", False):
                out = {"token": None}
            else:
                from ozone_tpu.storage.ids import BlockID
                from ozone_tpu.utils.security import (
                    AccessMode,
                    BlockTokenIssuer,
                )

                issuer = BlockTokenIssuer(scm.secret_keys)
                if op == "container-token":
                    tok = issuer.issue_container(int(target), owner="admin")
                else:
                    tok = issuer.issue(
                        BlockID.from_json(target),
                        [AccessMode.READ, AccessMode.WRITE], owner="admin")
                out = {"token": tok}
        elif op == "pipelines":
            out = {"pipelines": [
                {"id": p.id, "nodes": p.nodes,
                 "replication": str(p.replication),
                 "state": p.state.value}
                for p in scm.containers.pipelines()
            ]}
        elif op == "replication-status":
            from ozone_tpu.recon.recon import ReconScmView

            health = ReconScmView(scm).container_health()
            out = {k: len(v) for k, v in health.items()}
        elif op == "container-info":
            c = scm.containers.get_or_none(int(target))
            if c is None:
                raise StorageError("CONTAINER_NOT_FOUND",
                                   f"no container {target}")
            out = {
                "id": c.id,
                "state": c.state.value,
                "replication": str(c.replication),
                "pipeline": c.pipeline.id if c.pipeline else None,
                "nodes": c.pipeline.nodes if c.pipeline else [],
                "used_bytes": c.used_bytes,
                "replicas": [
                    {"dn_id": r.dn_id, "state": r.state,
                     "replica_index": r.replica_index,
                     "block_count": r.block_count,
                     "used_bytes": r.used_bytes}
                    for r in list(c.replicas.values())
                ],
            }
        elif op == "container-report":
            # ReplicationManagerReport analog (admin container report):
            # container-state census + replication-health census in one
            # view (tools/.../container/ReportSubcommand.java)
            from collections import Counter

            from ozone_tpu.recon.recon import ReconScmView

            states = Counter(
                c.state.value for c in scm.containers.containers())
            health = ReconScmView(scm).container_health()
            out = {
                "containers_total": sum(states.values()),
                "states": dict(states),
                "health": {k: len(v) for k, v in health.items()},
            }
        else:
            raise StorageError("UNSUPPORTED_REQUEST", f"admin op {op!r}")
        return wire.pack(out)

    def _list_containers(self, req: bytes) -> bytes:
        """Container listing for admin/repair tools (`ozone admin
        container list` analog)."""
        return wire.pack({
            "containers": [
                {
                    "id": c.id,
                    "state": c.state.value,
                    "replication": str(c.replication),
                    "nodes": c.pipeline.nodes if c.pipeline else [],
                    "used_bytes": c.used_bytes,
                    # snapshot: heartbeat threads mutate replicas live
                    "replicas": [
                        {"dn_id": r.dn_id, "state": r.state,
                         "replica_index": r.replica_index}
                        for r in list(c.replicas.values())
                    ],
                }
                for c in self.scm.containers.containers()
            ],
        })

    def _status(self, req: bytes) -> bytes:
        return wire.pack(
            {
                "safemode": self.scm.safemode.in_safemode(),
                "safemode_status": self.scm.safemode.status(),
                "block_tokens": getattr(self.scm, "block_tokens", False),
                "nodes": [
                    {
                        "dn_id": n.dn_id,
                        "rack": n.rack,
                        "state": n.state.value,
                        "op_state": n.op_state.value,
                        # usage columns (ozone admin datanode usageinfo):
                        "capacity_bytes": n.capacity_bytes,
                        "used_bytes": n.used_bytes,
                        "used_pct": round(
                            100.0 * n.used_bytes / n.capacity_bytes, 2)
                        if n.capacity_bytes else None,
                        "healthy_volumes": n.healthy_volumes,
                        "layout_version": n.layout_version,
                    }
                    for n in self.scm.nodes.nodes()
                ],
                "containers": len(self.scm.containers.containers()),
            }
        )


class GrpcScmClient:
    """Remote SCM client. `address` may be a comma-separated HA replica
    list: datanodes register/heartbeat to EVERY replica (the reference's
    datanodes heartbeat all SCMs so each tracks liveness and a promoted
    leader starts with fresh node state; commands only come back from the
    leader), while reads rotate to the first reachable replica."""

    def __init__(self, address: str, tls=None):
        from ozone_tpu.net.rpc import FailoverChannels

        self._pool = FailoverChannels(address, tls=tls)
        self.addresses = self._pool.addresses
        #: latest security fields seen on register/heartbeat responses
        #: ({"block_tokens": bool, "secret_keys": [...]}); the datanode
        #: daemon drains this into its verifier after each exchange
        self.security: dict = {}

    def _merge_security(self, responses: list[dict]) -> None:
        import time

        for m in responses:
            if m.get("ring"):
                # online ring growth AND retirement: adopt the full
                # shipped membership so removed replicas stop being
                # dialed on every heartbeat round
                self._pool.reconcile(m["ring"])
            if m.get("block_tokens"):
                self.security["block_tokens"] = True
                keys = {k["key_id"]: k
                        for k in self.security.get("secret_keys", [])}
                for k in m.get("secret_keys", []):
                    keys[k["key_id"]] = k
                now = time.time()  # expired keys must not accumulate
                self.security["secret_keys"] = [
                    k for k in keys.values() if k.get("expires", 0) >= now]

    def _call(self, method: str, meta: dict,
              timeout: Optional[float] = 30.0) -> dict:
        from ozone_tpu.client import resilience

        payload = wire.pack(meta)
        last: Optional[Exception] = None
        # backoff between failover attempts: during an election every
        # replica answers SCM_NOT_LEADER instantly, and a sleepless
        # loop burns the whole retry budget in milliseconds instead of
        # outliving the election. Tuning shared with the OM client —
        # see resilience.failover_retry_policy.
        attempts = max(4, 3 * len(self.addresses))
        policy = resilience.failover_retry_policy(attempts)
        for attempt in range(attempts):
            floor_s = None
            addr, ch = self._pool.channel()
            try:
                m, _ = wire.unpack(ch.call(
                    SERVICE, method, payload, timeout=timeout))
                return m
            except StorageError as e:
                last = e
                if e.code == "SCM_NOT_LEADER":
                    self._pool.follow_hint(e.msg)
                elif e.code == "UNAVAILABLE":
                    # drop the (possibly wedged) channel so the next
                    # attempt redials — see FailoverChannels.invalidate
                    self._pool.invalidate(addr)
                    if len(self.addresses) == 1:
                        raise
                    self._pool.rotate()
                elif e.code == resilience.SERVER_BUSY:
                    # healthy-peer pushback: back off to the server's
                    # Retry-After hint, same replica — see the OM client
                    floor_s = resilience.server_pushback_floor(e, "scm")
                else:
                    raise
            if not policy.sleep(attempt, floor_s=floor_s):
                resilience.check_deadline("scm_failover")
                break
        raise last

    def _broadcast(self, method: str, meta: dict,
                   timeout: Optional[float] = 2.0) -> list[dict]:
        """Send to every replica concurrently; return the successful
        responses (at least one required). Concurrency matters: a
        blackholed replica must cost one timeout in parallel, not one
        per replica per heartbeat."""
        payload = wire.pack(meta)

        def one(addr):
            _, ch = self._pool.channel(addr)
            try:
                m, _ = wire.unpack(ch.call(SERVICE, method, payload,
                                           timeout=timeout))
            except StorageError as e:
                if e.code == "UNAVAILABLE":
                    self._pool.invalidate(addr)  # redial next beat
                raise
            return m

        if len(self.addresses) == 1:
            return [one(self.addresses[0])]
        from concurrent.futures import ThreadPoolExecutor

        out, last = [], None
        with ThreadPoolExecutor(max_workers=len(self.addresses)) as ex:
            futs = {ex.submit(one, a): a for a in self.addresses}
            for f in futs:
                try:
                    out.append(f.result())
                except StorageError as e:
                    last = e
        if not out:
            raise last
        return out

    def register(self, dn_id: str, address: str, rack: str = "/default-rack",
                 capacity_bytes: int = 0,
                 op_state: Optional[str] = None) -> None:
        responses = self._broadcast("Register", {
            "dn_id": dn_id, "address": address, "rack": rack,
            "capacity_bytes": capacity_bytes, "op_state": op_state,
        })
        self._merge_security(responses)

    def heartbeat(self, dn_id: str, container_report=None,
                  used_bytes: int = 0,
                  deleted_block_acks: Optional[list[int]] = None,
                  layout_version: Optional[int] = None,
                  healthy_volumes: Optional[int] = None) -> list:
        responses = self._broadcast("Heartbeat", {
            "dn_id": dn_id,
            "container_report": container_report,
            "used_bytes": used_bytes,
            "deleted_block_acks": deleted_block_acks or [],
            "layout_version": layout_version,
            "healthy_volumes": healthy_volumes,
        })
        self._merge_security(responses)
        cmds = []
        for m in responses:  # only the leader queues commands
            cmds.extend(deserialize_command(c) for c in m["commands"])
        return cmds

    def allocate_block(self, replication: str, block_size: int,
                       excluded: Optional[list[str]] = None):
        m = self._call("AllocateBlock", {
            "replication": replication,
            "block_size": block_size,
            "excluded": excluded or [],
        })
        return m["group"], m["addresses"]

    def list_containers(self) -> list[dict]:
        return self._call("ListContainers", {})["containers"]

    def node_addresses(self) -> dict[str, str]:
        return self._call("NodeAddresses", {})["addresses"]

    def node_topology(self) -> tuple[dict[str, str], dict[str, str]]:
        """(addresses, locations) from ONE NodeAddresses round-trip."""
        m = self._call("NodeAddresses", {})
        return m["addresses"], m.get("locations", {})

    def node_locations(self) -> dict[str, str]:
        """dn_id -> topology location (for nearest-first read ordering)."""
        return self.node_topology()[1]

    def admin(self, op: str, target: Optional[str] = None) -> dict:
        return self._call("AdminOp", {"op": op, "target": target})

    def status(self) -> dict:
        return self._call("Status", {})

    def close(self) -> None:
        self._pool.close()


class AdminTokenFetcher:
    """TokenStore issuer that fetches operator tokens from the SCM
    (SCMSecurityProtocol.getContainerToken analog) — lets dn-direct
    debug/repair/freon tools run against token-enforcing clusters
    without holding the secret keys. On insecure clusters the SCM
    answers None and requests go out untokened."""

    def __init__(self, scm_client: GrpcScmClient):
        self.scm = scm_client

    def issue(self, block_id, modes=None, owner="admin"):
        try:
            return self.scm.admin(
                "block-token", block_id.to_json()).get("token")
        except StorageError:
            return None

    def issue_container(self, container_id, modes=None, owner="admin"):
        try:
            return self.scm.admin(
                "container-token", int(container_id)).get("token")
        except StorageError:
            return None
