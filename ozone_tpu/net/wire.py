"""Wire format for the gRPC datapath: JSON header + raw payload bytes.

The reference's datapath messages are protobuf (DatanodeClientProtocol
.proto) with chunk payloads as embedded bytes. Here each RPC carries a
compact length-prefixed JSON header (verbs' metadata is small) followed by
the raw chunk payload, so bulk data is never re-encoded — the property
that matters at GiB/s rates. grpc-python passes requests/responses as raw
bytes when serializers are None, so no codegen plugin is needed.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Optional

import numpy as np

_LEN = struct.Struct("!I")


def pack(meta: dict[str, Any], payload: Optional[bytes | np.ndarray] = None) -> bytes:
    h = json.dumps(meta, separators=(",", ":")).encode()
    if payload is None:
        return _LEN.pack(len(h)) + h
    if isinstance(payload, np.ndarray):
        # zero-copy into the join for the hot shape (contiguous uint8);
        # tobytes() would pay a full extra copy per chunk
        body = (memoryview(payload) if payload.dtype == np.uint8
                and payload.flags.c_contiguous else payload.tobytes())
    else:
        body = payload  # bytes/bytearray/memoryview join without copy
    return b"".join((_LEN.pack(len(h)), h, body))


def unpack(buf: bytes) -> tuple[dict[str, Any], memoryview]:
    (hlen,) = _LEN.unpack_from(buf, 0)
    meta = json.loads(bytes(buf[4 : 4 + hlen]).decode())
    return meta, memoryview(buf)[4 + hlen :]


def payload_array(view: memoryview) -> np.ndarray:
    return np.frombuffer(view, dtype=np.uint8)
