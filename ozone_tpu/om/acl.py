"""Ozone-style ACLs: volume/bucket/key/prefix grants + native authorizer.

Capability mirror of the reference's ACL stack: `OzoneAcl` (common
OzoneAcl.java: type USER/GROUP/WORLD, name, rights bitset, scope
ACCESS/DEFAULT), `PrefixManagerImpl` (ozone-manager PrefixManagerImpl:
ACLs attached to path prefixes, longest-prefix match), and
`OzoneNativeAuthorizer` (native authorizer consulted by every OM request
when `ozone.acl.enabled` is on; off by default — same default here).

Storage shape: volume/bucket/key rows carry an `acls` list; prefix grants
live in the `prefixes` table keyed `/vol/bucket/prefix/`. DEFAULT-scoped
grants on a parent are inherited as ACCESS grants by children created
beneath it (the reference's OzoneAclUtil.inheritDefaultAcls).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Iterable, Optional

from ozone_tpu.om.metadata import OMMetadataStore, bucket_key, volume_key


class ACLRight(Enum):
    READ = "r"
    WRITE = "w"
    CREATE = "c"
    LIST = "l"
    DELETE = "d"
    READ_ACL = "x"
    WRITE_ACL = "y"

    @classmethod
    def all(cls) -> frozenset["ACLRight"]:
        return frozenset(cls)


_RIGHT_BY_LETTER = {r.value: r for r in ACLRight}


class ACLIdentityType(Enum):
    USER = "user"
    GROUP = "group"
    WORLD = "world"


class ACLScope(Enum):
    ACCESS = "ACCESS"
    DEFAULT = "DEFAULT"


@dataclass(frozen=True)
class OzoneAcl:
    """One grant. String form matches the reference CLI:
    `user:alice:rwcl[ACCESS]`, `world::r` (scope defaults to ACCESS),
    rights letter `a` = all."""

    id_type: ACLIdentityType
    name: str  # empty for WORLD
    rights: frozenset[ACLRight]
    scope: ACLScope = ACLScope.ACCESS

    @classmethod
    def parse(cls, s: str) -> "OzoneAcl":
        scope = ACLScope.ACCESS
        if s.endswith("]") and "[" in s:
            s, _, sc = s[:-1].rpartition("[")
            scope = ACLScope(sc.upper())
        parts = s.split(":")
        if len(parts) != 3:
            raise ValueError(f"acl must be type:name:rights, got {s!r}")
        t, name, letters = parts
        if letters == "a":
            rights = ACLRight.all()
        else:
            rights = frozenset(_RIGHT_BY_LETTER[ch] for ch in letters)
        return cls(ACLIdentityType(t.lower()), name, rights, scope)

    def __str__(self) -> str:
        letters = ("a" if self.rights == ACLRight.all() else
                   "".join(sorted(r.value for r in self.rights)))
        return f"{self.id_type.value}:{self.name}:{letters}[{self.scope.value}]"

    def to_json(self) -> dict:
        return {
            "type": self.id_type.value,
            "name": self.name,
            "rights": sorted(r.value for r in self.rights),
            "scope": self.scope.value,
        }

    @classmethod
    def from_json(cls, d: dict) -> "OzoneAcl":
        return cls(
            ACLIdentityType(d["type"]),
            d.get("name", ""),
            frozenset(_RIGHT_BY_LETTER[x] for x in d["rights"]),
            ACLScope(d.get("scope", "ACCESS")),
        )

    def matches(self, user: str, groups: Iterable[str]) -> bool:
        if self.id_type is ACLIdentityType.WORLD:
            return True
        if self.id_type is ACLIdentityType.USER:
            return self.name == user
        return self.name in set(groups)


def add_acl(acls: list[dict], new: OzoneAcl) -> tuple[list[dict], bool]:
    """Merge a grant into a stored acl list (rights union per identity,
    reference OzoneAclUtil.addAcl). Returns (updated, changed)."""
    out = []
    merged = False
    changed = False
    for d in acls:
        a = OzoneAcl.from_json(d)
        if (a.id_type, a.name, a.scope) == (new.id_type, new.name, new.scope):
            u = OzoneAcl(a.id_type, a.name, a.rights | new.rights, a.scope)
            changed = u.rights != a.rights
            out.append(u.to_json())
            merged = True
        else:
            out.append(d)
    if not merged:
        out.append(new.to_json())
        changed = True
    return out, changed


def remove_acl(acls: list[dict], gone: OzoneAcl) -> tuple[list[dict], bool]:
    """Subtract rights; identities left with no rights drop out."""
    out = []
    changed = False
    for d in acls:
        a = OzoneAcl.from_json(d)
        if (a.id_type, a.name, a.scope) == (gone.id_type, gone.name,
                                            gone.scope):
            kept = a.rights - gone.rights
            changed = changed or kept != a.rights
            if kept:
                out.append(OzoneAcl(a.id_type, a.name, kept, a.scope).to_json())
        else:
            out.append(d)
    return out, changed


def inherit_defaults(parent_acls: list[dict]) -> list[dict]:
    """DEFAULT grants on the parent become ACCESS grants on a new child
    (OzoneAclUtil.inheritDefaultAcls)."""
    out = []
    for d in parent_acls:
        a = OzoneAcl.from_json(d)
        if a.scope is ACLScope.DEFAULT:
            out.append(OzoneAcl(a.id_type, a.name, a.rights,
                                ACLScope.ACCESS).to_json())
    return out


def prefix_key(volume: str, bucket: str, prefix: str) -> str:
    if not prefix.endswith("/"):
        prefix += "/"
    return f"/{volume}/{bucket}/{prefix}"


def normalize_acls(acls: Optional[Iterable]) -> list[dict]:
    """Accept OzoneAcl objects, CLI strings, or json dicts -> json dicts
    (shared by the local OM facade and the gRPC client)."""
    out: list[dict] = []
    for a in acls or []:
        if isinstance(a, OzoneAcl):
            out.append(a.to_json())
        elif isinstance(a, str):
            out.append(OzoneAcl.parse(a).to_json())
        else:
            out.append(a)
    return out


class NativeAuthorizer:
    """OzoneNativeAuthorizer analog: evaluates a requested right against
    the grant chain volume -> bucket -> longest matching prefixes -> key.

    Semantics follow the reference: the owner of the volume and the
    superuser always pass; otherwise the *deepest* object that carries
    explicit ACCESS grants for the caller decides; prefix grants override
    bucket grants for keys underneath them.
    """

    def __init__(self, store: OMMetadataStore, superusers: Iterable[str] = ("root",)):
        self.store = store
        self.superusers = set(superusers)

    def _explicit(self, acls: Optional[list], user: str,
                  groups: Iterable[str], right: ACLRight) -> Optional[bool]:
        """True/False if any grant names this caller, None if no grant
        mentions them at this level."""
        if not acls:
            return None
        mentioned = False
        for d in acls:
            a = OzoneAcl.from_json(d)
            if a.scope is not ACLScope.ACCESS:
                continue
            if a.matches(user, groups):
                mentioned = True
                if right in a.rights:
                    return True
        return False if mentioned else None

    def check(self, volume: str, bucket: Optional[str], key: Optional[str],
              user: str, groups: Iterable[str], right: ACLRight) -> bool:
        if user in self.superusers:
            return True
        vrow = self.store.get("volumes", volume_key(volume))
        if vrow is None:
            return False
        if vrow.get("owner") == user:
            return True
        decision = self._explicit(vrow.get("acls"), user, groups, right)
        if bucket is not None:
            brow = self.store.get("buckets", bucket_key(volume, bucket))
            if brow is not None:
                d = self._explicit(brow.get("acls"), user, groups, right)
                if d is not None:
                    decision = d
                if brow.get("owner") == user:
                    return True
        if key is not None:
            # longest-prefix-first scan of prefix grants under the bucket
            base = f"/{volume}/{bucket}/"
            best_len = -1
            for pk, prow in self.store.iterate("prefixes", base):
                p = pk[len(base):]
                if (key + "/").startswith(p) and len(p) > best_len:
                    d = self._explicit(prow.get("acls"), user, groups, right)
                    if d is not None:
                        decision = d
                        best_len = len(p)
            # key row: flat table for OBS, parent-id-keyed files for FSO
            # (same resolution as requests._acl_target)
            from ozone_tpu.om import requests as rq

            try:
                table, k = rq._acl_target(self.store, "key", volume,
                                          bucket, key)
                krow = self.store.get(table, k)
            except rq.OMError:
                krow = None
            if krow is not None:
                d = self._explicit(krow.get("acls"), user, groups, right)
                if d is not None:
                    decision = d
        return bool(decision)


from ozone_tpu.om.requests import OMError, PERMISSION_DENIED  # noqa: E402


class ACLDeniedError(OMError):
    """An OMError (code PERMISSION_DENIED) so denials flow through the
    request log, the gRPC error mapping, and client failover untouched."""

    def __init__(self, user: str, right: ACLRight, path: str):
        super().__init__(PERMISSION_DENIED,
                         f"user {user} lacks {right.name} on {path}")
        self.user = user
        self.right = right
        self.path = path
