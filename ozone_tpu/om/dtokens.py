"""OM delegation tokens: store-backed, HMAC-signed identity tokens.

Mirror of the reference's delegation-token stack
(hadoop-ozone/ozone-manager .../security/OzoneDelegationTokenSecretManager.java,
OzoneTokenIdentifier in hadoop-ozone/common): a client authenticated once
obtains a token naming an owner and a renewer; the token then
authenticates later OM calls (jobs run without the original credential),
can be renewed by its renewer up to a hard max lifetime, and cancelled by
its owner or renewer. The reference persists both the rotating master
keys and the live tokens in OM RocksDB tables so tokens survive restart
and verify identically on every HA replica; here the same state lives in
the replicated OMMetadataStore tables `dtoken_keys` and
`delegation_tokens`, mutated only through OMRequests so the ring stays
convergent.

The signed identifier is a flat dict: owner, renewer, real_user, issue,
max_date, token_id, key_id — signature = HMAC-SHA256(master key,
canonical JSON of those fields). Renewable expiry is server-side state
(the row), not part of the signature, exactly like the reference where
renewal updates the stored renew date without re-issuing the token.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import time
from typing import Any, Optional

#: identifier fields covered by the signature, in canonical order
IDENT_FIELDS = ("owner", "renewer", "real_user", "issue", "max_date",
                "token_id", "key_id")

TOKEN_ERROR = "TOKEN_ERROR"


class DTokenError(Exception):
    def __init__(self, msg: str):
        super().__init__(msg)
        self.msg = msg


def canonical(ident: dict) -> bytes:
    return json.dumps(
        {f: ident.get(f) for f in IDENT_FIELDS},
        sort_keys=True, separators=(",", ":"),
    ).encode()


def sign(material: bytes, ident: dict) -> str:
    return hmac.new(material, canonical(ident), hashlib.sha256).hexdigest()


def current_key(store, now: Optional[float] = None) -> Optional[dict]:
    """Newest unexpired master key, or None. Deterministic given `now`
    (request apply paths pass the request's own timestamp)."""
    now = time.time() if now is None else now
    best = None
    for _, row in store.iterate("dtoken_keys"):
        if row["expires"] <= now:
            continue
        if best is None or row["created"] > best["created"]:
            best = row
    return best


def check_signature(store, token: Any) -> dict:
    """Signature + shape check only (no liveness): raises DTokenError or
    returns the token dict. Used before renew/cancel so a forged token
    can never reach the replicated log."""
    if not isinstance(token, dict):
        raise DTokenError("malformed delegation token")
    for f in IDENT_FIELDS:
        if f not in token:
            raise DTokenError(f"delegation token missing field {f!r}")
    key = store.get("dtoken_keys", str(token["key_id"]))
    if key is None:
        raise DTokenError("delegation token signed by unknown master key")
    expect = sign(bytes.fromhex(key["material"]), token)
    if not hmac.compare_digest(expect, str(token.get("sig", ""))):
        raise DTokenError("bad delegation token signature")
    return token


def verify(store, token: Any, now: Optional[float] = None) -> dict:
    """Full verification: signature, live row, renewable expiry. Returns
    the STORED row (authoritative owner/renewer/expiry)."""
    check_signature(store, token)
    row = store.get("delegation_tokens", str(token["token_id"]))
    if row is None:
        raise DTokenError("delegation token cancelled or unknown")
    now = time.time() if now is None else now
    if row["expiry"] < now:
        raise DTokenError("delegation token expired (renew lapsed)")
    if row["max_date"] < now:
        raise DTokenError("delegation token past max lifetime")
    return row
