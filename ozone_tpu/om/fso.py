"""FILE_SYSTEM_OPTIMIZED (FSO) bucket layout: a true directory tree.

Capability mirror of the reference's FSO layout (ozone-manager
BucketLayoutAwareOMKeyRequestFactory.java routes key requests to
OMFileCreateRequest / OMDirectoryCreateRequest / OMKeyRenameRequestWithFSO
variants; interface-storage OMMetadataManager.java:375-642 defines the
directoryTable/fileTable keyed by parent object id). Entries are stored as

    dirs :  /{volume}/{bucket}/{parentId}/{name} -> {object_id, ...}
    files:  /{volume}/{bucket}/{parentId}/{name} -> key info

so a directory rename is O(1) — only the directory's own entry moves;
children key off its immutable object id. Recursive delete moves the dir
entry to the deleted_dirs table and a background DirectoryDeletingService
(reference: service/DirectoryDeletingService.java) walks the subtree,
feeding files into the deleted-key purge chain.

Object ids are allocated in pre_execute on the leader and carried inside
the request so follower applies are deterministic (the OMClientRequest
preExecute/validateAndUpdateCache contract, OMClientRequest.java:114,143).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ozone_tpu.om.metadata import OMMetadataStore, bucket_key
from ozone_tpu.om.requests import (
    BUCKET_NOT_FOUND,
    KEY_NOT_FOUND,
    OMError,
    OMRequest,
    finalize_commit,
)

DIRECTORY_NOT_FOUND = "DIRECTORY_NOT_FOUND"
DIRECTORY_NOT_EMPTY = "DIRECTORY_NOT_EMPTY"
NOT_A_FILE = "NOT_A_FILE"
NOT_A_DIRECTORY = "NOT_A_DIRECTORY"
FILE_ALREADY_EXISTS = "FILE_ALREADY_EXISTS"

ROOT_ID = "0"  # every bucket's root directory object id


def split_path(path: str) -> list[str]:
    parts = [p for p in path.strip("/").split("/") if p]
    for p in parts:
        if p in (".", ".."):
            raise OMError(NOT_A_FILE, f"illegal path component {p!r}")
    return parts


def dir_key(volume: str, bucket: str, parent_id: str, name: str) -> str:
    return f"/{volume}/{bucket}/{parent_id}/{name}"


def id_key(volume: str, bucket: str, object_id: str) -> str:
    return f"/{volume}/{bucket}/{object_id}"


def dir_alive(
    store: OMMetadataStore, volume: str, bucket: str, object_id: str
) -> bool:
    return object_id == ROOT_ID or store.exists(
        "dir_ids", id_key(volume, bucket, object_id)
    )


def resolve(
    store: OMMetadataStore, volume: str, bucket: str, path: str
) -> tuple[str, list[str]]:
    """Walk the directory tree; return (deepest existing dir's object id,
    unresolved trailing components)."""
    parts = split_path(path)
    parent = ROOT_ID
    for i, name in enumerate(parts):
        d = store.get("dirs", dir_key(volume, bucket, parent, name))
        if d is None:
            return parent, parts[i:]
        parent = d["object_id"]
    return parent, []


def resolve_parent(
    store: OMMetadataStore, volume: str, bucket: str, path: str
) -> tuple[str, str]:
    """Resolve the parent directory of `path`; return (parent_id, name).
    Raises DIRECTORY_NOT_FOUND if an intermediate component is missing."""
    parts = split_path(path)
    if not parts:
        raise OMError(NOT_A_FILE, "empty path")
    parent, missing = resolve(store, volume, bucket, "/".join(parts[:-1]))
    if missing:
        raise OMError(DIRECTORY_NOT_FOUND, "/".join(parts[:-1]))
    return parent, parts[-1]


def _require_bucket(store: OMMetadataStore, volume: str, bucket: str) -> dict:
    b = store.get("buckets", bucket_key(volume, bucket))
    if b is None:
        raise OMError(BUCKET_NOT_FOUND, f"{volume}/{bucket}")
    return b


def _ensure_parents(
    store: OMMetadataStore,
    volume: str,
    bucket: str,
    parts: list[str],
    new_ids: list[str],
    created: float,
    conflict_code: str,
) -> str:
    """Create any missing directory components along `parts`, using the
    leader-assigned `new_ids` for determinism; return the final dir's
    object id. A file occupying a component raises `conflict_code`."""
    parent = ROOT_ID
    for i, name in enumerate(parts):
        dk = dir_key(volume, bucket, parent, name)
        d = store.get("dirs", dk)
        if d is None:
            if store.exists("files", dk):
                raise OMError(conflict_code, dk)
            from ozone_tpu.om.requests import preserve_fso_preimage

            d = {
                "object_id": new_ids[i],
                "name": name,
                "parent_id": parent,
                "created": created,
            }
            idk = id_key(volume, bucket, d["object_id"])
            preserve_fso_preimage(store, volume, bucket, "dirs", dk)
            preserve_fso_preimage(store, volume, bucket, "dir_ids", idk)
            store.put("dirs", dk, d)
            store.put("dir_ids", idk,
                      {"parent_id": parent, "name": name})
        parent = d["object_id"]
    return parent


@dataclass
class CreateDirectory(OMRequest):
    """mkdir -p: creates all missing components (OMDirectoryCreateRequest
    with MissingParentInfos, reference request/file/)."""

    volume: str
    bucket: str
    path: str
    # ids pre-allocated on the leader, one per possibly-missing component
    new_ids: list[str] = field(default_factory=list)
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()
        self.new_ids = [
            uuid.uuid4().hex[:16] for _ in split_path(self.path)
        ]

    def apply(self, store):
        _require_bucket(store, self.volume, self.bucket)
        return _ensure_parents(
            store, self.volume, self.bucket, split_path(self.path),
            self.new_ids, self.created, FILE_ALREADY_EXISTS,
        )


@dataclass
class OpenFile(OMRequest):
    """Open a file for write, creating missing parent dirs
    (OMFileCreateRequest semantics)."""

    volume: str
    bucket: str
    path: str
    client_id: str
    replication: str
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    overwrite: bool = True
    new_dir_ids: list[str] = field(default_factory=list)
    created: float = 0.0
    metadata: dict = field(default_factory=dict)
    #: envelope-encryption bundle (TDE EDEK / GDPR secret) minted by
    #: the OM at open — see requests.OpenKey.encryption
    encryption: dict = field(default_factory=dict)
    #: stable identity of this file version (OmKeyInfo objectID) —
    #: rename-carried, overwrite-fresh; snapdiff pairs rows by it
    file_id: str = ""
    #: explicit ACLs fixed at open — see requests.OpenKey.acls
    acls: list = field(default_factory=list)

    def pre_execute(self, om) -> None:
        self.created = time.time()
        self.new_dir_ids = [
            uuid.uuid4().hex[:16] for _ in split_path(self.path)
        ]
        self.file_id = uuid.uuid4().hex[:16]

    def apply(self, store):
        _require_bucket(store, self.volume, self.bucket)
        parts = split_path(self.path)
        if not parts:
            raise OMError(NOT_A_FILE, "empty path")
        parent = _ensure_parents(
            store, self.volume, self.bucket, parts[:-1],
            self.new_dir_ids, self.created, NOT_A_DIRECTORY,
        )
        name = parts[-1]
        fk = dir_key(self.volume, self.bucket, parent, name)
        if store.exists("dirs", fk):
            raise OMError(NOT_A_FILE, f"{fk} is a directory")
        if not self.overwrite and store.exists("files", fk):
            raise OMError(FILE_ALREADY_EXISTS, fk)
        row = {
            "volume": self.volume,
            "bucket": self.bucket,
            "name": self.path.strip("/"),
            "object_id": self.file_id,
            "file_name": name,
            "parent_id": parent,
            "replication": self.replication,
            "checksum_type": self.checksum_type,
            "bytes_per_checksum": self.bytes_per_checksum,
            "size": 0,
            "block_groups": [],
            "created": self.created,
            "modified": self.created,
        }
        if self.metadata:
            row["metadata"] = dict(self.metadata)
        if self.acls:
            row["acls"] = list(self.acls)
        if self.encryption:
            row["encryption"] = dict(self.encryption)
        store.put("open_keys", f"{fk}/{self.client_id}", row)
        return parent


@dataclass
class CommitFile(OMRequest):
    """Move an open-file session into the file table (OMFileCreateRequest's
    commit counterpart, keyed by parent object id)."""

    volume: str
    bucket: str
    parent_id: str
    file_name: str
    client_id: str
    size: int
    block_groups: list[dict] = field(default_factory=list)
    modified: float = 0.0
    hsync: bool = False
    #: rewrite fence — see CommitKey.expect_object_id
    expect_object_id: str = ""
    expect_generation: int = -1

    def pre_execute(self, om) -> None:
        self.modified = time.time()

    def apply(self, store):
        fk = dir_key(self.volume, self.bucket, self.parent_id, self.file_name)
        open_k = f"{fk}/{self.client_id}"
        info = store.get("open_keys", open_k)
        if info is None:
            raise OMError(KEY_NOT_FOUND, f"no open session {open_k}")
        if not dir_alive(store, self.volume, self.bucket, self.parent_id):
            # parent was recursively deleted while the key was open; refuse
            # the commit so the file row can't become unreachable, and hand
            # the already-written blocks to the deleted-key purge chain
            store.delete("open_keys", open_k)
            info.update(size=self.size, block_groups=self.block_groups)
            from ozone_tpu.om.requests import erase_gdpr_secret

            erase_gdpr_secret(info)
            store.put("deleted_keys", f"{fk}:{self.modified}", info)
            raise OMError(DIRECTORY_NOT_FOUND,
                          f"parent of {fk} deleted during write")
        info.update(
            {
                "size": self.size,
                "block_groups": self.block_groups,
                "modified": self.modified,
            }
        )
        old = store.get("files", fk)
        from ozone_tpu.om.requests import check_rewrite_fence

        check_rewrite_fence(store, self.expect_object_id, old, open_k,
                            fk, info, self.modified,
                            self.expect_generation)
        finalize_commit(store, "files", fk, info, old, self.client_id,
                        self.hsync, self.modified)
        return info


@dataclass
class DeleteFile(OMRequest):
    volume: str
    bucket: str
    path: str
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        parent, name = resolve_parent(store, self.volume, self.bucket, self.path)
        fk = dir_key(self.volume, self.bucket, parent, name)
        info = store.get("files", fk)
        if info is None:
            if store.exists("dirs", fk):
                raise OMError(NOT_A_FILE, f"{fk} is a directory")
            raise OMError(KEY_NOT_FOUND, fk)
        from ozone_tpu.om.requests import preserve_fso_preimage

        preserve_fso_preimage(store, self.volume, self.bucket,
                              "files", fk)
        store.delete("files", fk)
        # fence a live hsync stream before purging its blocks
        stale_writer = info.get("hsync_client_id")
        if stale_writer:
            store.delete("open_keys", f"{fk}/{stale_writer}")
        from ozone_tpu.om.requests import erase_gdpr_secret

        erase_gdpr_secret(info)
        store.put("deleted_keys", f"{fk}:{self.ts}", info)
        from ozone_tpu.om.requests import check_and_charge_quota

        check_and_charge_quota(store, self.volume, self.bucket,
                               -int(info.get("size", 0)), -1)
        return info


@dataclass
class DeleteDirectory(OMRequest):
    """Detach a directory (recursive) or remove an empty one. The subtree
    is purged asynchronously by DirectoryDeletingService — matching the
    reference where OMKeyDeleteRequestWithFSO moves the dir into the
    deletedDirectoryTable."""

    volume: str
    bucket: str
    path: str
    recursive: bool = False
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        parent, name = resolve_parent(store, self.volume, self.bucket, self.path)
        dk = dir_key(self.volume, self.bucket, parent, name)
        d = store.get("dirs", dk)
        if d is None:
            raise OMError(DIRECTORY_NOT_FOUND, dk)
        prefix = f"/{self.volume}/{self.bucket}/{d['object_id']}/"
        has_children = (
            next(store.iterate("dirs", prefix), None) is not None
            or next(store.iterate("files", prefix), None) is not None
        )
        if has_children and not self.recursive:
            raise OMError(DIRECTORY_NOT_EMPTY, dk)
        from ozone_tpu.om.requests import preserve_fso_preimage

        idk = id_key(self.volume, self.bucket, d["object_id"])
        preserve_fso_preimage(store, self.volume, self.bucket, "dirs", dk)
        preserve_fso_preimage(store, self.volume, self.bucket,
                              "dir_ids", idk)
        store.delete("dirs", dk)
        store.delete("dir_ids", idk)
        store.put(
            "deleted_dirs",
            f"/{self.volume}/{self.bucket}/{d['object_id']}:{self.ts}",
            {"volume": self.volume, "bucket": self.bucket, **d},
        )


@dataclass
class SetEntryAttrs(OMRequest):
    """Merge filesystem attributes (owner/group/permission/mtime/atime)
    into a file or directory row (the FSO side of HttpFS SETOWNER /
    SETPERMISSION / SETTIMES). A None value deletes the attribute;
    `preconds` enforces the xattr CREATE/REPLACE flags atomically."""

    volume: str
    bucket: str
    path: str
    attrs: dict
    preconds: dict = field(default_factory=dict)

    def apply(self, store):
        from ozone_tpu.om.requests import check_attr_preconds

        parent, name = resolve_parent(
            store, self.volume, self.bucket, self.path
        )
        ek = dir_key(self.volume, self.bucket, parent, name)
        table = "dirs" if store.exists("dirs", ek) else "files"
        info = store.get(table, ek)
        if info is None:
            raise OMError(KEY_NOT_FOUND, ek)
        from ozone_tpu.om.requests import preserve_fso_preimage

        preserve_fso_preimage(store, self.volume, self.bucket, table, ek)
        check_attr_preconds(info, self.preconds)
        merged = dict(info.get("attrs", {}))
        for k, v in self.attrs.items():
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        info["attrs"] = merged
        store.put(table, ek, info)
        return info


@dataclass
class RenameEntry(OMRequest):
    """Rename a file or directory. Directory rename moves ONE row — the
    whole subtree follows because children are keyed by the directory's
    object id (OMKeyRenameRequestWithFSO)."""

    volume: str
    bucket: str
    src: str
    dst: str
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        src_parent, src_name = resolve_parent(
            store, self.volume, self.bucket, self.src
        )
        dst_parent, dst_name = resolve_parent(
            store, self.volume, self.bucket, self.dst
        )
        sk = dir_key(self.volume, self.bucket, src_parent, src_name)
        dk = dir_key(self.volume, self.bucket, dst_parent, dst_name)
        if store.exists("dirs", dk) or store.exists("files", dk):
            raise OMError(FILE_ALREADY_EXISTS, dk)
        d = store.get("dirs", sk)
        if d is not None:
            # moving a dir under its own subtree would orphan it
            p = dst_parent
            while p != ROOT_ID:
                if p == d["object_id"]:
                    raise OMError(NOT_A_DIRECTORY,
                                  f"cannot move {sk} into its own subtree")
                p = _parent_of(store, self.volume, self.bucket, p)
            from ozone_tpu.om.requests import (
                newest_snapshot,
                preserve_fso_preimage,
            )

            idk = id_key(self.volume, self.bucket, d["object_id"])
            nw = newest_snapshot(store, self.volume, self.bucket)
            preserve_fso_preimage(store, self.volume, self.bucket,
                                  "dirs", sk, newest=nw)
            preserve_fso_preimage(store, self.volume, self.bucket,
                                  "dirs", dk, newest=nw)
            preserve_fso_preimage(store, self.volume, self.bucket,
                                  "dir_ids", idk, newest=nw)
            d.update(name=dst_name, parent_id=dst_parent, modified=self.ts)
            store.delete("dirs", sk)
            store.put("dirs", dk, d)
            store.put("dir_ids", idk,
                      {"parent_id": dst_parent, "name": dst_name})
            return d
        f = store.get("files", sk)
        if f is None:
            raise OMError(KEY_NOT_FOUND, sk)
        from ozone_tpu.om.requests import preserve_fso_preimage

        preserve_fso_preimage(store, self.volume, self.bucket, "files", sk)
        preserve_fso_preimage(store, self.volume, self.bucket, "files", dk)
        f.update(file_name=dst_name, parent_id=dst_parent, modified=self.ts)
        store.delete("files", sk)
        store.put("files", dk, f)
        return f


def _parent_of(
    store: OMMetadataStore, volume: str, bucket: str, object_id: str
) -> str:
    """O(1) parent lookup via the dir_ids index (rename-cycle check)."""
    e = store.get("dir_ids", id_key(volume, bucket, object_id))
    return e["parent_id"] if e else ROOT_ID


@dataclass
class PurgeDirectories(OMRequest):
    """Apply one batch of DirectoryDeletingService work: move files under
    deleted dirs into deleted_keys, re-queue child dirs, drop finished
    entries (reference service/DirectoryDeletingService.java purge path)."""

    # [(deleted_dirs key to drop, [(file key, info)...], [(child dir key, info)...])]
    drops: list[str] = field(default_factory=list)
    file_moves: list[list] = field(default_factory=list)  # [files key, info, ts]
    dir_moves: list[list] = field(default_factory=list)  # [deleted_dirs key, info]

    def apply(self, store):
        from ozone_tpu.om.requests import (
            check_and_charge_quota,
            erase_gdpr_secret,
        )

        from ozone_tpu.om.requests import (
            newest_snapshot,
            preserve_fso_preimage,
        )

        # one snapmeta scan per bucket for the whole batch
        newest_cache: dict = {}

        def _newest(vol0, bkt0):
            key = (vol0, bkt0)
            if key not in newest_cache:
                newest_cache[key] = newest_snapshot(store, vol0, bkt0)
            return newest_cache[key]

        for fk, info, ts in self.file_moves:
            _, vol0, bkt0 = fk.split("/", 3)[:3]
            preserve_fso_preimage(store, vol0, bkt0, "files", fk,
                                  newest=_newest(vol0, bkt0))
            store.delete("files", fk)
            erase_gdpr_secret(info)
            store.put("deleted_keys", f"{fk}:{ts}", info)
            _, vol, bkt = fk.split("/", 3)[:3]
            check_and_charge_quota(store, vol, bkt,
                                   -int(info.get("size", 0)), -1)
        for dk, info in self.dir_moves:
            idk = id_key(info["volume"], info["bucket"],
                         info["object_id"])
            nw = _newest(info["volume"], info["bucket"])
            preserve_fso_preimage(store, info["volume"], info["bucket"],
                                  "dirs", dk, newest=nw)
            preserve_fso_preimage(store, info["volume"], info["bucket"],
                                  "dir_ids", idk, newest=nw)
            store.delete("dirs", dk)
            store.delete("dir_ids", idk)
            store.put("deleted_dirs", dk_suffix(dk, info), info)
        for k in self.drops:
            # re-check emptiness at apply time: a file committed between the
            # service's scan and this apply must not be orphaned
            info = store.get("deleted_dirs", k)
            if info is not None:
                prefix = (f"/{info['volume']}/{info['bucket']}/"
                          f"{info['object_id']}/")
                if (next(store.iterate("files", prefix), None) is not None
                        or next(store.iterate("dirs", prefix), None)
                        is not None):
                    continue  # keep queued; next pass collects the stragglers
            store.delete("deleted_dirs", k)


def dk_suffix(dk: str, info: dict) -> str:
    return f"/{info['volume']}/{info['bucket']}/{info['object_id']}:{info.get('ts', 0)}"


class DirectoryDeletingService:
    """Background subtree reaper. Each run() pass collects up to `limit`
    children of detached directories and submits one PurgeDirectories
    request (so HA replicas stay in sync)."""

    def __init__(self, om):
        self.om = om

    def run_once(self, limit: int = 256) -> int:
        store = self.om.store
        drops: list[str] = []
        file_moves: list[list] = []
        dir_moves: list[list] = []
        n = 0
        ts = time.time()
        for ddk, d in list(store.iterate("deleted_dirs")):
            if n >= limit:
                break
            vol, bkt = d["volume"], d["bucket"]
            prefix = f"/{vol}/{bkt}/{d['object_id']}/"
            exhausted = True
            for fk, info in store.iterate("files", prefix):
                file_moves.append([fk, info, ts])
                n += 1
                if n >= limit:
                    exhausted = False
                    break
            if exhausted:
                for dk, child in store.iterate("dirs", prefix):
                    dir_moves.append(
                        [dk, {"volume": vol, "bucket": bkt, "ts": ts, **child}]
                    )
                    n += 1
                    if n >= limit:
                        exhausted = False
                        break
            if exhausted:
                drops.append(ddk)
                n += 1
        if not (drops or file_moves or dir_moves):
            return 0
        self.om.submit(
            PurgeDirectories(
                drops=drops, file_moves=file_moves, dir_moves=dir_moves
            )
        )
        return n

    def run_to_completion(self, max_rounds: int = 1000) -> int:
        total = 0
        for _ in range(max_rounds):
            got = self.run_once()
            if got == 0:
                return total
            total += got
        return total


# --------------------------------------------------------------- read paths
def get_status(
    store: OMMetadataStore, volume: str, bucket: str, path: str
) -> dict:
    """getFileStatus: file or directory info (reference
    KeyManagerImpl.getFileStatus)."""
    parts = split_path(path)
    if not parts:
        return {"type": "DIRECTORY", "name": "", "object_id": ROOT_ID}
    parent, missing = resolve(store, volume, bucket, "/".join(parts[:-1]))
    if missing:
        raise OMError(KEY_NOT_FOUND, path)
    ek = dir_key(volume, bucket, parent, parts[-1])
    d = store.get("dirs", ek)
    if d is not None:
        return {"type": "DIRECTORY", **d, "name": "/".join(parts)}
    f = store.get("files", ek)
    if f is not None:
        # 'name' is derived from the traversal, never from the stored row —
        # ancestors may have been renamed since the file was written
        return {"type": "FILE", **f, "name": "/".join(parts)}
    raise OMError(KEY_NOT_FOUND, path)


def _list_children(
    store: OMMetadataStore, volume: str, bucket: str, object_id: str,
    base: str,
) -> list[dict]:
    """Immediate children of a directory known by object id — no path
    re-resolution. Dirs first then files, each sorted by name."""
    prefix = f"/{volume}/{bucket}/{object_id}/"
    out = []
    for _, d in store.iterate("dirs", prefix):
        full = f"{base}/{d['name']}" if base else d["name"]
        out.append({"type": "DIRECTORY", **d, "path": full, "name": full})
    for _, f in store.iterate("files", prefix):
        full = f"{base}/{f['file_name']}" if base else f["file_name"]
        out.append({"type": "FILE", **f, "path": full, "name": full})
    return out


def list_status(
    store: OMMetadataStore, volume: str, bucket: str, path: str
) -> list[dict]:
    """listStatus: immediate children of a directory (or the file itself)."""
    st = get_status(store, volume, bucket, path)
    if st["type"] != "DIRECTORY":
        return [st]
    return _list_children(store, volume, bucket, st["object_id"],
                          "/".join(split_path(path)))


def walk_files_paged(
    store: OMMetadataStore, volume: str, bucket: str,
    prefix: str = "", start_after: str = "",
    limit: Optional[int] = None,
) -> list[dict]:
    """Lexicographic path-order file walk with subtree pruning: a
    directory is descended only if its path range can still contain
    entries matching `prefix` and beyond `start_after`; the walk stops
    once `limit` files are collected. This is the paged listKeys backend
    for FSO buckets — a page costs O(page + touched-directory scans),
    not a full-tree walk."""
    out: list[dict] = []
    if limit is not None and limit <= 0:
        return out

    def _children_window(object_id: str, base: str, floor: str,
                         include_floor_dir: bool):
        """One bounded sibling window of a directory, name-ordered with
        dirs expanding at their path position. `floor` is the sibling
        name to resume from (exclusive for files; the dir of that name
        is included when the cursor descends into it)."""
        kprefix = f"/{volume}/{bucket}/{object_id}/"
        want = None if limit is None else (limit - len(out) + 1)
        ents = []
        if include_floor_dir and floor:
            bd = store.get("dirs", dir_key(volume, bucket, object_id,
                                           floor))
            if bd is not None:
                full = f"{base}/{floor}" if base else floor
                ents.append({"type": "DIRECTORY", **bd, "path": full,
                             "name": full})
        sa = (kprefix + floor) if floor else ""
        drained = True
        for table, kind in (("dirs", "DIRECTORY"), ("files", "FILE")):
            rows = store.iterate_range(table, kprefix, start_after=sa,
                                       limit=want)
            if want is not None and len(rows) >= want:
                drained = False
            for _, e in rows:
                nm = e["name"] if kind == "DIRECTORY" else e["file_name"]
                full = f"{base}/{nm}" if base else nm
                ents.append({"type": kind, **e, "path": full,
                             "name": full})
        ents.sort(key=lambda e: e["name"] +
                  ("/" if e["type"] == "DIRECTORY" else ""))
        return ents, drained

    def _walk(object_id: str, base: str) -> bool:
        """Returns True when the limit is reached (stop unwinding)."""
        # resume floor: the next path segment of the cursor inside this
        # directory (pushed into the store scan so a page never re-reads
        # already-served siblings)
        floor = ""
        if start_after:
            if not base:
                floor = start_after.split("/", 1)[0]
            elif start_after.startswith(base + "/"):
                floor = start_after[len(base) + 1:].split("/", 1)[0]
        include_floor_dir = True
        while True:
            ents, drained = _children_window(object_id, base, floor,
                                             include_floor_dir)
            include_floor_dir = False
            for e in ents:
                floor = max(floor, e["name"].rsplit("/", 1)[-1])
                if e["type"] == "FILE":
                    name = e["name"]
                    if prefix and not name.startswith(prefix):
                        continue
                    if start_after and name <= start_after:
                        continue
                    out.append(e)
                    if limit is not None and len(out) >= limit:
                        return True
                else:
                    p = e["name"] + "/"
                    # prune: subtree cannot match the prefix
                    if prefix and not (p.startswith(prefix)
                                       or prefix.startswith(p)):
                        continue
                    # prune: every descendant sorts before the cursor
                    if (start_after and start_after > p
                            and not start_after.startswith(p)):
                        continue
                    if _walk(e["object_id"], e["path"]):
                        return True
            if drained or not ents:
                return False

    _walk(ROOT_ID, "")
    return out


def lookup_file(
    store: OMMetadataStore, volume: str, bucket: str, path: str
) -> dict:
    st = get_status(store, volume, bucket, path)
    if st["type"] != "FILE":
        raise OMError(NOT_A_FILE, path)
    return st
