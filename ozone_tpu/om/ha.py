"""OM high availability: replicated request log + failover client.

Capability mirror of the reference's OM HA stack (ozone-manager om/ratis/:
OzoneManagerRatisServer.submitRequest:108 ships post-preExecute requests
through Raft; OzoneManagerStateMachine.applyTransaction:335 applies them
deterministically on every replica against the metadata store; clients
fail over between OMs via the OMFailoverProxyProvider).

Two consensus modes share the same request lifecycle — preExecute on the
leader, serialized request through a durable ordered log, deterministic
apply everywhere (the reference's pluggable-consensus shape; SURVEY.md
section 7 explicitly stages consensus behind the request/apply split):

- `RaftOzoneManager`: full quorum consensus (consensus/raft.py) — leader
  elections with terms, quorum-committed log, conflict repair, snapshot
  bootstrap. This is the complete Ratis-equivalent mode.
- `ReplicatedOzoneManager`: single-leader synchronous replication with
  operator-driven promote() failover — the degenerate consensus useful
  for two-replica or orchestrator-managed deployments.

Both keep a durable JSONL WAL per replica with fsync-on-append and
replay-on-restart from the last flushed transaction (the
OzoneManagerDoubleBuffer + TransactionInfo recovery pattern).
"""

from __future__ import annotations

import json
import logging
import threading
from pathlib import Path
from typing import Any, Optional

from ozone_tpu.om import requests as rq
from ozone_tpu.om.om import OzoneManager

log = logging.getLogger(__name__)


class RequestLog:
    """Durable ordered request log (Raft-log stand-in)."""

    def __init__(self, path: Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(self.path, "a+")
        self._lock = threading.Lock()
        self._index = sum(1 for _ in open(self.path))

    @property
    def index(self) -> int:
        return self._index

    def append(self, entry: dict) -> int:
        with self._lock:
            self._f.write(json.dumps(entry, separators=(",", ":")) + "\n")
            self._f.flush()
            import os

            os.fsync(self._f.fileno())
            self._index += 1
            return self._index

    def read_from(self, start: int = 0) -> list[dict]:
        with self._lock:
            self._f.flush()
        out = []
        with open(self.path) as f:
            for i, line in enumerate(f):
                if i >= start and line.strip():
                    out.append(json.loads(line))
        return out

    def close(self) -> None:
        self._f.close()


class ReplicatedOzoneManager:
    """One OM replica: leader accepts writes, followers apply the log."""

    def __init__(self, om: OzoneManager, log_path: Path, om_id: str,
                 is_leader: bool = False):
        self.om = om
        self.om_id = om_id
        self.is_leader = is_leader
        self.wal = RequestLog(log_path)
        self.applied_index = 0
        self.peers: list["ReplicatedOzoneManager"] = []
        self._lock = threading.RLock()
        self._recover()

    # ------------------------------------------------------------- recovery
    def _recover(self) -> None:
        """Replay the local log onto the store (idempilot: requests that
        already applied raise OMErrors which are ignored during replay —
        the cache/DB state converges because applies are deterministic)."""
        entries = self.wal.read_from(0)
        for e in entries:
            try:
                rq.OMRequest.from_json(e["request"]).apply(self.om.store)
            except rq.OMError:  # ozlint: allow[error-swallowing] -- deterministic replay: already-applied entries refuse, state converges (docstring)
                pass
            self.applied_index = e["index"]

    # ------------------------------------------------------------- serving
    def submit(self, request: rq.OMRequest) -> Any:
        """Leader write path: preExecute -> log -> replicate -> apply."""
        with self._lock:
            if not self.is_leader:
                raise NotLeaderError(self.om_id)
            request.pre_execute(self.om)
            entry = {
                "index": self.wal.index + 1,
                "request": request.to_json(),
            }
            self.wal.append(entry)
            for peer in self.peers:
                try:
                    peer.replicate(entry)
                except Exception:
                    log.exception("replication to %s failed", peer.om_id)
            result = request.apply(self.om.store)
            self.applied_index = entry["index"]
            return result

    def replicate(self, entry: dict) -> None:
        """Follower apply path (applyTransaction analog)."""
        with self._lock:
            if entry["index"] <= self.applied_index:
                return  # duplicate
            if entry["index"] != self.applied_index + 1:
                self.catch_up()
                if entry["index"] != self.applied_index + 1:
                    raise ValueError(
                        f"log gap: at {self.applied_index}, got "
                        f"{entry['index']}"
                    )
            self.wal.append(entry)
            try:
                rq.OMRequest.from_json(entry["request"]).apply(self.om.store)
            except rq.OMError as e:
                # deterministic failures also happen on the leader; keep
                # the index advancing
                log.debug("follower apply error: %s", e)
            self.applied_index = entry["index"]

    def catch_up(self) -> None:
        """Pull missing entries from the leader (follower bootstrap /
        InterSCMGrpcProtocolService-style checkpoint+delta catch-up)."""
        leader = next((p for p in self.peers if p.is_leader), None)
        if leader is None:
            return
        for e in leader.wal.read_from(self.applied_index):
            if e["index"] > self.applied_index:
                self.wal.append(e)
                try:
                    rq.OMRequest.from_json(e["request"]).apply(self.om.store)
                except rq.OMError:  # ozlint: allow[error-swallowing] -- deterministic catch-up replay, same contract as _replay
                    pass
                self.applied_index = e["index"]

    # ------------------------------------------------------------- failover
    def promote(self) -> None:
        """Make this replica the leader (after catching up)."""
        self.catch_up()
        for p in self.peers:
            p.is_leader = False
        self.is_leader = True
        log.info("om %s promoted to leader at index %d", self.om_id,
                 self.applied_index)


class NotLeaderError(Exception):
    pass


class RaftOzoneManager:
    """OM replica on quorum consensus — the full OzoneManagerRatisServer
    analog (ozone-manager om/ratis/OzoneManagerRatisServer.java:108):
    leader elections with terms and randomized timeouts, replicated log
    with quorum commit, deterministic applyTransaction on every replica,
    and snapshot-based follower bootstrap (consensus/raft.py).

    Request lifecycle matches the reference exactly: `submit` runs
    preExecute on the leader (block allocation, normalization), proposes
    the serialized request through Raft, and returns the local apply
    result once the entry commits. Deterministic OMErrors replicate like
    any result so every replica's table state stays byte-identical.
    """

    def __init__(
        self,
        om: OzoneManager,
        raft_dir: Path,
        om_id: str,
        peer_ids: list[str],
        transport=None,
        config=None,
    ):
        from ozone_tpu.consensus.raft import RaftConfig, RaftNode

        self.om = om
        self.om_id = om_id
        self.node = RaftNode(
            om_id,
            peer_ids,
            Path(raft_dir),
            apply_fn=self._apply,
            snapshot_fn=om.store.export_state,
            restore_fn=om.store.import_state,
            config=config or RaftConfig(),
            transport=transport,
        )

    def _apply(self, data: dict) -> Any:
        return rq.OMRequest.from_json(data).apply(self.om.store)

    @property
    def is_leader(self) -> bool:
        return self.node.is_leader

    def submit(self, request: rq.OMRequest) -> Any:
        if not self.node.is_leader:
            raise NotLeaderError(self.om_id)
        request.pre_execute(self.om)
        result = self.node.propose(request.to_json())
        if isinstance(result, Exception):
            raise result
        return result

    def start(self) -> None:
        self.node.start_timers()

    def stop(self) -> None:
        self.node.stop()


class OMFailoverProxy:
    """Client-side failover across OM replicas (OMFailoverProxyProvider
    analog): tries the known leader first, rotates on NotLeaderError or
    connection failure."""

    def __init__(self, replicas: list):
        self.replicas = replicas
        self._leader_idx = 0

    def submit(self, request: rq.OMRequest) -> Any:
        from ozone_tpu.consensus.raft import NotRaftLeaderError

        last: Optional[Exception] = None
        n = len(self.replicas)
        for attempt in range(n):
            idx = (self._leader_idx + attempt) % n
            try:
                result = self.replicas[idx].submit(request)
                self._leader_idx = idx
                return result
            except (NotLeaderError, NotRaftLeaderError, ConnectionError,
                    OSError) as e:
                last = e
        raise RuntimeError(f"no OM leader reachable: {last}")
