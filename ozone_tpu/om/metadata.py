"""OM metadata store: volumes/buckets/keys tables with write-batched flush.

Mirrors the reference's OmMetadataManagerImpl table layout (volume, bucket,
key, openKey, deleted tables — interface-storage OMMetadataManager.java:
375-642) over sqlite instead of RocksDB, and the OzoneManagerDoubleBuffer
throughput pattern (om/ratis/OzoneManagerDoubleBuffer.java:72,
flushTransactions:293): applied transactions mutate an in-memory cache
immediately and are flushed to sqlite in batches, so the apply path never
waits on storage.
"""

from __future__ import annotations

import contextlib
import json
import sqlite3
import threading
from pathlib import Path
from typing import Any, Iterator, Optional

_TABLES = (
    "volumes",
    "buckets",
    "keys",
    "open_keys",
    "deleted_keys",
    # FSO layout tables (dir/file entries keyed by parent object id,
    # reference interface-storage OMMetadataManager.java:375-642)
    "dirs",
    "dir_ids",  # object_id -> {parent_id, name}: O(1) liveness/ancestry
    "files",
    "deleted_dirs",
    "multipart",
    # accessId -> secret for S3 SigV4 auth (reference: OM s3SecretTable
    # backing the s3-secret-store module)
    "s3_secrets",
    # path-prefix ACL grants (reference: prefixTable / PrefixManagerImpl)
    "prefixes",
    # multi-tenancy (reference: tenantStateTable, tenantAccessIdTable)
    "tenants",
    "tenant_access",
    # delegation tokens (reference: dTokenTable + persisted master keys,
    # OzoneDelegationTokenSecretManager)
    "delegation_tokens",
    "dtoken_keys",
    # process-level markers (e.g. the raft applied-index floor) that must
    # flush atomically with the data they describe
    "system",
    # small-object slabs (Haystack/f4 needle volumes): one row per sealed
    # slab — its EC block groups plus the needle directory, keyed
    # /volume/bucket/slab_id so a slab rides its bucket's shard slot
    "slabs",
)

#: tables with a maintained rolling state digest (the replica-divergence
#: canary reads it O(1) instead of rescanning the table per sample)
_DIGEST_TABLES = ("keys",)


def _row_hash(key: str, value: dict) -> int:
    return _row_hash_json(key, json.dumps(value, sort_keys=True))


def _row_hash_json(key: str, dumped: str) -> int:
    import hashlib

    h = hashlib.md5()
    h.update(key.encode())
    h.update(b"\0")
    h.update(dumped.encode())
    return int.from_bytes(h.digest(), "big")


class OMMetadataStore:
    def __init__(self, db_path: Path, flush_every: int = 64):
        self._path = Path(db_path)
        self._path.parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(str(self._path), check_same_thread=False)
        for t in _TABLES:
            self._conn.execute(
                f"CREATE TABLE IF NOT EXISTS {t} (k TEXT PRIMARY KEY, v TEXT)"
            )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.commit()
        self._lock = threading.RLock()
        # table -> key -> value-or-None(=tombstone); the double buffer
        self._cache: dict[str, dict[str, Optional[dict]]] = {
            t: {} for t in _TABLES
        }
        self._dirty: list[tuple[str, str, Optional[dict]]] = []
        self.flush_every = flush_every
        self._txid = 0
        # bounded update journal for WAL-delta shipping (the reference's
        # DBUpdatesWrapper: Recon tails OM RocksDB WAL deltas instead of
        # rescanning). Entries: (txid, table, key, value-or-None).
        self._updates: list[tuple[int, str, str, Optional[dict]]] = []
        self.max_journal = 100_000
        #: process-local snapshot markers (snap_id -> txid at snapshot
        #: apply), feeding the incremental snapshot diff. Deliberately
        #: NOT replicated state: each replica's journal positions are
        #: its own, and the markers are exactly as durable as the
        #: in-memory journal they index — when either is gone the diff
        #: falls back to the full listing comparison.
        self.snapshot_markers: dict[str, int] = {}
        # group-commit coordination (flush_group): _txid doubles as the
        # apply sequence; _flushed_txid/_flushing live under _flush_cv
        self._flush_cv = threading.Condition()
        self._flushed_txid = 0
        self._flushing = False
        # atomic() nesting depth: >0 defers the flush_every auto-flush
        self._defer = 0
        # rolling per-table digests (XOR of per-row hashes): O(1) to
        # read, O(1) to maintain per mutation — the divergence canary
        # must not pay an O(table) rescan inside the serialized apply
        # path (round-4 advisor finding). Persisted in `system` within
        # the same sqlite commit as the rows it describes, so a reopened
        # store trusts the row; absent (pre-upgrade dbs) -> one
        # recompute scan at open.
        self._digests: dict[str, int] = {}
        # hash of each UNFLUSHED digested row as it was digested, keyed
        # (table, key); 0 = digested as absent. The old-row hash must
        # never be recomputed from the write-back cache: callers mutate
        # fetched dicts in place before put(), so the cached "old" dict
        # can alias the new value and the XOR would cancel.
        self._digest_hashes: dict[tuple[str, str], int] = {}
        for t in _DIGEST_TABLES:
            row = self._conn.execute(
                "SELECT v FROM system WHERE k=?", (f"__digest_{t}",)
            ).fetchone()
            if row is not None:
                self._digests[t] = int(json.loads(row[0])["xor"], 16)
            else:
                self._digests[t] = self._scan_digest(t)

    def _scan_digest(self, table: str) -> int:
        d = 0
        for k, v in self._conn.execute(f"SELECT k, v FROM {table}"):
            d ^= _row_hash(k, json.loads(v))
        return d

    def table_digest(self, table: str) -> str:
        """Deterministic state digest of a digested table (equal states
        -> equal digests across replicas; XOR of row hashes, so the
        value is independent of mutation order)."""
        with self._lock:
            return f"{self._digests[table]:032x}"

    def _digest_mutate(self, table: str, key: str,
                       dumped: Optional[str]) -> None:
        """Caller holds self._lock; `dumped` is the canonical dump of
        the new value (None = delete). The old-row hash comes from the
        unflushed-hash map or a direct sqlite point read — NEVER from
        the write-back cache, whose dicts alias values callers mutate
        in place before put() (the XOR would cancel and the digest
        silently diverge from the table)."""
        if table not in self._digests:
            return
        hk = (table, key)
        old = self._digest_hashes.get(hk)
        if old is None:
            row = self._conn.execute(
                f"SELECT v FROM {table} WHERE k=?", (key,)).fetchone()
            old = _row_hash(key, json.loads(row[0])) if row else 0
        new = _row_hash_json(key, dumped) if dumped is not None else 0
        self._digests[table] ^= old ^ new
        self._digest_hashes[hk] = new

    # ------------------------------------------------------------------ CRUD
    @contextlib.contextmanager
    def atomic(self):
        """One request's mutations land in ONE durable batch: the
        flush_every auto-flush is deferred inside the block, so a
        multi-row apply (rename's delete+put, a multipart commit) can
        never be SPLIT across sqlite commits by the batch boundary — a
        crash between the halves would tear the request (a renamed key
        readable under NEITHER name, and replay cannot redo it because
        the re-apply deterministically fails KEY_NOT_FOUND). The
        reference gets this from the RocksDB double buffer: one batch
        per transaction (OzoneManagerDoubleBuffer.flushTransactions).
        Explicit flush()/flush_group() calls still flush — they commit
        whole batches, which is exactly the guarantee."""
        with self._lock:
            self._defer += 1
        try:
            yield
        finally:
            with self._lock:
                self._defer -= 1
                if not self._defer and \
                        len(self._dirty) >= self.flush_every:
                    self._flush_locked()

    def put(self, table: str, key: str, value: dict,
            journal: bool = True) -> None:
        """`journal=False` skips the update journal (NOT durability):
        bulk derived writes — snapshot materialization copies O(bucket)
        rows — would otherwise evict the live-mutation history that
        WAL-delta consumers (Recon, incremental snapdiff) depend on."""
        # serialize at put time: the flushed row is then byte-identical
        # to what was digested even if the caller keeps mutating the
        # dict after put() (the cache serves the live dict either way)
        dumped = json.dumps(value, sort_keys=True)
        with self._lock:
            self._digest_mutate(table, key, dumped)
            self._cache[table][key] = value
            self._dirty.append((table, key, dumped))
            self._txid += 1
            if journal:
                self._journal(table, key, value)
            if not self._defer and len(self._dirty) >= self.flush_every:
                self._flush_locked()

    def delete(self, table: str, key: str, journal: bool = True) -> None:
        with self._lock:
            self._digest_mutate(table, key, None)
            self._cache[table][key] = None
            self._dirty.append((table, key, None))
            self._txid += 1
            if journal:
                self._journal(table, key, None)
            if not self._defer and len(self._dirty) >= self.flush_every:
                self._flush_locked()

    def _journal(self, table: str, key: str, value: Optional[dict]) -> None:
        self._updates.append((self._txid, table, key, value))
        if len(self._updates) > self.max_journal:
            del self._updates[: len(self._updates) // 2]

    def get_updates_since(
        self, txid: int
    ) -> tuple[list[tuple[int, str, str, Optional[dict]]], int, bool]:
        """WAL-delta shipping (DBUpdatesWrapper analog): updates after
        `txid`, the current txid, and whether the journal still reaches
        back that far (False -> consumer must full-rescan, the same
        contract as RocksDB WAL retention)."""
        import bisect

        with self._lock:
            complete = (
                txid >= (self._updates[0][0] - 1) if self._updates
                else txid >= self._txid
            )
            # txids are strictly increasing: binary-search the offset
            # instead of scanning the whole journal under the store lock
            i = bisect.bisect_right(self._updates, txid, key=lambda u: u[0])
            return self._updates[i:], self._txid, complete

    def get(self, table: str, key: str) -> Optional[dict]:
        with self._lock:
            if key in self._cache[table]:
                return self._cache[table][key]
            row = self._conn.execute(
                f"SELECT v FROM {table} WHERE k=?", (key,)
            ).fetchone()
            return json.loads(row[0]) if row else None

    def exists(self, table: str, key: str) -> bool:
        return self.get(table, key) is not None

    def count(self, table: str) -> int:
        """Row count without materializing rows: SQL COUNT(*) adjusted
        by the (bounded, <= flush_every) write-back cache — insights
        endpoints must not deserialize millions of rows to report a
        number."""
        with self._lock:
            n = self._conn.execute(
                f"SELECT COUNT(*) FROM {table}").fetchone()[0]
            for k, v in self._cache[table].items():
                in_db = self._conn.execute(
                    f"SELECT 1 FROM {table} WHERE k=?", (k,)
                ).fetchone() is not None
                if v is None and in_db:
                    n -= 1
                elif v is not None and not in_db:
                    n += 1
            return n

    def iterate(
        self, table: str, prefix: str = ""
    ) -> Iterator[tuple[str, dict]]:
        """Sorted iteration merging cache over sqlite (prefix scan)."""
        yield from self.iterate_range(table, prefix)

    def iterate_range(
        self, table: str, prefix: str = "", start_after: str = "",
        limit: Optional[int] = None,
    ) -> list[tuple[str, dict]]:
        """Bounded sorted scan: rows under `prefix` with key >
        `start_after`, at most `limit` (None = all) — the paged-listing
        backend. The SQL window over-fetches by the write-back cache's
        size so cached deletions can never displace a row out of the
        window; merged rows beyond a truncated SQL horizon are dropped
        to keep ordering exact."""
        with self._lock:
            floor = start_after or ""
            cache_rows = {
                k: v
                for k, v in self._cache[table].items()
                if k.startswith(prefix) and k > floor
            }
            sql_limit = -1 if limit is None else limit + len(cache_rows)
            if floor and floor >= prefix:
                cond, bound = "k > ?", floor
            else:
                cond, bound = "k >= ?", prefix
            db_rows = self._conn.execute(
                f"SELECT k, v FROM {table} WHERE {cond} AND k < ? "
                f"ORDER BY k LIMIT ?",
                (bound, prefix + "￿", sql_limit),
            ).fetchall()
            merged: dict[str, Optional[dict]] = {
                k: json.loads(v) for k, v in db_rows
            }
            merged.update(cache_rows)
            out = [(k, merged[k]) for k in sorted(merged)
                   if merged[k] is not None]
            if (limit is not None and len(db_rows) == sql_limit
                    and db_rows):
                horizon = db_rows[-1][0]
                out = [kv for kv in out if kv[0] <= horizon]
            if limit is not None:
                out = out[: max(0, limit)]
            return out

    # ------------------------------------------------------------------ flush
    def flush(self) -> None:
        with self._lock:
            seq = self._txid
            self._flush_locked()
        with self._flush_cv:
            self._flushed_txid = max(self._flushed_txid, seq)
            self._flush_cv.notify_all()

    def flush_group(self) -> None:
        """Group commit: make everything THIS caller applied durable,
        batching with whatever concurrent callers applied meanwhile —
        one sqlite commit (one fsync) covers them all. The reference's
        OzoneManagerDoubleBuffer.flushTransactions:293 trick: client
        futures complete only after the batch lands, but many requests
        share one durable batch write. One thread flushes; the rest
        wait for a flush covering their apply sequence."""
        with self._lock:
            target = self._txid
        while True:
            with self._flush_cv:
                if self._flushed_txid >= target:
                    return
                if not self._flushing:
                    self._flushing = True
                    break
                self._flush_cv.wait(timeout=5.0)
            # woken uncovered: the previous flusher finished without
            # covering us (or FAILED) — loop and become the flusher
            # ourselves. An error therefore never wedges the write
            # path: every caller either gets a covering durable flush
            # or its OWN exception from its own attempt.
        seq = 0
        ok = False
        try:
            with self._lock:
                seq = self._txid
                self._flush_locked()
            ok = True
        finally:
            with self._flush_cv:
                self._flushing = False
                if ok:
                    self._flushed_txid = max(self._flushed_txid, seq)
                self._flush_cv.notify_all()

    def _flush_locked(self) -> None:
        if not self._dirty:
            return
        batch, self._dirty = self._dirty, []
        cur = self._conn.cursor()
        for table, key, dumped in batch:
            if dumped is None:
                cur.execute(f"DELETE FROM {table} WHERE k=?", (key,))
            else:
                cur.execute(
                    f"INSERT OR REPLACE INTO {table} VALUES (?, ?)",
                    (key, dumped),
                )
        # digest rows ride the same commit as the rows they describe, so
        # a crash can never leave them disagreeing with the table
        for t, d in self._digests.items():
            cur.execute(
                "INSERT OR REPLACE INTO system VALUES (?, ?)",
                (f"__digest_{t}", json.dumps({"xor": f"{d:032x}"})),
            )
        self._conn.commit()
        # cache entries are now durable; drop them so memory stays bounded
        flushed = {(t, k) for t, k, _ in batch}
        for t, k in flushed:
            self._cache[t].pop(k, None)
            # flushed rows are re-hashable from sqlite (they now hold
            # exactly the dump that was digested)
            self._digest_hashes.pop((t, k), None)

    # --------------------------------------------------------------- snapshot
    def export_state(self) -> dict:
        """Full-table dump for HA snapshot shipping (the OM follower
        bootstrap checkpoint — OMDBCheckpointServlet analog)."""
        with self._lock:
            self._flush_locked()
            return {
                "txid": self._txid,
                "tables": {
                    t: {k: v for k, v in self.iterate(t)} for t in _TABLES
                },
            }

    def import_state(self, state: dict) -> None:
        """Replace all tables with a shipped checkpoint."""
        with self._lock:
            self._dirty.clear()
            self._updates.clear()
            self._digest_hashes.clear()
            # shipped markers would index the SENDER's journal, not ours
            self.snapshot_markers.clear()
            cur = self._conn.cursor()
            for t in _TABLES:
                self._cache[t].clear()
                cur.execute(f"DELETE FROM {t}")
                for k, v in state["tables"].get(t, {}).items():
                    cur.execute(
                        f"INSERT OR REPLACE INTO {t} VALUES (?, ?)",
                        (k, json.dumps(v)),
                    )
            self._conn.commit()
            self._txid = max(self._txid, int(state.get("txid", 0)))
            # the shipped system table carries the sender's digest rows
            # for exactly the tables just installed; absent (older
            # sender) -> recompute from the installed rows
            shipped = state["tables"].get("system", {})
            for t in self._digests:
                row = shipped.get(f"__digest_{t}")
                self._digests[t] = (int(row["xor"], 16) if row
                                    else self._scan_digest(t))

    @property
    def txid(self) -> int:
        return self._txid

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            self._conn.close()


def volume_key(volume: str) -> str:
    return f"/{volume}"


def bucket_key(volume: str, bucket: str) -> str:
    return f"/{volume}/{bucket}"


def key_key(volume: str, bucket: str, key: str) -> str:
    return f"/{volume}/{bucket}/{key}"


def slab_key(volume: str, bucket: str, slab_id: str) -> str:
    """Slabs are bucket-scoped rows: the whole needle directory of a
    slab lives on the shard that owns its bucket's slot, so a batched
    multi-key commit touches exactly one shard ring."""
    return f"/{volume}/{bucket}/{slab_id}"
