"""OM multipart upload: initiate / commit-part / complete / abort, plus
the open-key and MPU expiry cleanup services.

Mirror of the reference's MPU chain (hadoop-ozone/client RpcClient.java:
2009 createMultipartKey and the S3InitiateMultipartUpload /
S3MultipartUploadCommitPart / S3MultipartUploadComplete /
S3MultipartUploadAbort request classes in ozone-manager request/s3/
multipart/): upload state lives in the OM multipart table keyed by
/volume/bucket/key/uploadId; each part carries its own block groups;
complete stitches parts in part-number order into the final key entry and
routes every replaced or orphaned part's blocks into the deleted-keys
purge chain (nothing leaks on the datanodes).

Expiry services mirror OpenKeyCleanupService and
MultipartUploadCleanupService (ozone-manager service/): both scan for
entries older than a threshold and submit the same deterministic requests
a client abort would.
"""

from __future__ import annotations

import logging
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ozone_tpu.om.metadata import bucket_key, key_key
from ozone_tpu.om import requests as rq

log = logging.getLogger(__name__)

NO_SUCH_UPLOAD = "NO_SUCH_MULTIPART_UPLOAD"
INVALID_PART = "INVALID_PART"


def mpu_key(volume: str, bucket: str, key: str, upload_id: str) -> str:
    return f"{key_key(volume, bucket, key)}/{upload_id}"


def _final_etag(listed: list[dict]) -> str:
    """S3-style composite etag from the stored (validated) parts, so the
    result is content-derived regardless of whether the complete request
    carried etags."""
    import hashlib

    joined = "".join(p["etag"] for p in listed)
    return hashlib.md5(joined.encode()).hexdigest() + f"-{len(listed)}"


def _release_blocks(store, info: dict, ts: float, tag: str) -> None:
    """Route a part/key entry's blocks into the deleted-keys purge chain."""
    if info.get("block_groups"):
        rq.erase_gdpr_secret(info)
        store.put("deleted_keys", f"{tag}:{ts}", info)


@dataclass
class InitiateMultipartUpload(rq.OMRequest):
    volume: str
    bucket: str
    key: str
    upload_id: str = ""
    replication: str = ""
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    created: float = 0.0
    metadata: dict = field(default_factory=dict)
    #: LEGACY bucket: key pre-normalized; enforce filesystem shape
    fs_paths: bool = False
    #: TDE/GDPR: one envelope bundle for the whole upload; each part
    #: encrypts independently under it with a per-part IV
    encryption: dict = field(default_factory=dict)

    def pre_execute(self, om) -> None:
        self.created = time.time()
        if not self.upload_id:
            self.upload_id = uuid.uuid4().hex
        if not self.replication:
            self.replication = om.bucket_info(self.volume, self.bucket)[
                "replication"
            ]

    def apply(self, store):
        if not store.exists("buckets", bucket_key(self.volume, self.bucket)):
            raise rq.OMError(
                rq.BUCKET_NOT_FOUND, f"{self.volume}/{self.bucket}"
            )
        if self.fs_paths:
            rq.check_fs_conflicts(store, self.volume, self.bucket,
                                  self.key)
        store.put(
            "multipart",
            mpu_key(self.volume, self.bucket, self.key, self.upload_id),
            {
                "volume": self.volume,
                "bucket": self.bucket,
                "name": self.key,
                "upload_id": self.upload_id,
                "replication": self.replication,
                "checksum_type": self.checksum_type,
                "bytes_per_checksum": self.bytes_per_checksum,
                "created": self.created,
                "parts": {},
                "metadata": dict(self.metadata),
                **({"encryption": dict(self.encryption)}
                   if self.encryption else {}),
            },
        )
        return self.upload_id


@dataclass
class CommitMultipartPart(rq.OMRequest):
    """Record one uploaded part (S3MultipartUploadCommitPartRequest):
    re-uploading a part number replaces it, and the replaced part's
    blocks go to the purge chain."""

    volume: str
    bucket: str
    key: str
    upload_id: str
    part_number: int
    size: int
    etag: str
    block_groups: list[dict] = field(default_factory=list)
    ts: float = 0.0
    #: CTR IV this part's ciphertext was produced with (encrypted MPU)
    iv: str = ""

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        mk = mpu_key(self.volume, self.bucket, self.key, self.upload_id)
        mpu = store.get("multipart", mk)
        if mpu is None:
            raise rq.OMError(NO_SUCH_UPLOAD, mk)
        part_no = str(self.part_number)
        old = mpu["parts"].get(part_no)
        if old is not None:
            _release_blocks(store, old, self.ts, f"{mk}/part{part_no}")
        mpu["parts"][part_no] = {
            "volume": self.volume,
            "bucket": self.bucket,
            "part_number": self.part_number,
            "size": self.size,
            "etag": self.etag,
            "block_groups": self.block_groups,
            "modified": self.ts,
            **({"iv": self.iv} if self.iv else {}),
        }
        store.put("multipart", mk, mpu)
        return self.etag


@dataclass
class CompleteMultipartUpload(rq.OMRequest):
    """Stitch listed parts, in part-number order, into the final key
    (S3MultipartUploadCompleteRequest): parts must exist with matching
    etags and be listed in ascending order; uploaded-but-unlisted parts
    and any overwritten previous key version are purged."""

    volume: str
    bucket: str
    key: str
    upload_id: str
    parts: list[dict] = field(default_factory=list)  # {part_number, etag}
    ts: float = 0.0
    #: LEGACY bucket: enforce filesystem shape on the final key
    fs_paths: bool = False
    #: stable identity of the assembled key version (OmKeyInfo objectID)
    key_id: str = ""

    def pre_execute(self, om) -> None:
        self.ts = time.time()
        self.key_id = uuid.uuid4().hex[:16]

    def apply(self, store):
        mk = mpu_key(self.volume, self.bucket, self.key, self.upload_id)
        mpu = store.get("multipart", mk)
        if mpu is None:
            raise rq.OMError(NO_SUCH_UPLOAD, mk)
        if self.fs_paths:
            # re-checked at complete time (the namespace may have
            # changed since initiate); quota for the markers joins the
            # key's single upfront charge below
            rq.check_fs_conflicts(store, self.volume, self.bucket,
                                  self.key)
        listed: list[dict] = []
        prev = 0
        for p in self.parts:
            n = int(p["part_number"])
            if n <= prev:
                raise rq.OMError(
                    INVALID_PART, f"part numbers not ascending at {n}"
                )
            prev = n
            part = mpu["parts"].get(str(n))
            if part is None or part["etag"] != p.get("etag", part["etag"]):
                raise rq.OMError(INVALID_PART, f"part {n}")
            listed.append(part)
        if not listed:
            raise rq.OMError(INVALID_PART, "no parts listed")
        kk = key_key(self.volume, self.bucket, self.key)
        old = store.get("keys", kk)
        # before ANY mutation of the aliased old row (_release_blocks
        # erases its GDPR secret in place)
        rq.preserve_preimage(store, self.volume, self.bucket, kk)
        markers = (rq.missing_parent_markers(store, self.volume,
                                             self.bucket, self.key)
                   if self.fs_paths else [])
        # quota precedes EVERY mutation: a QUOTA_EXCEEDED complete must
        # leave the upload fully intact for a retry after space is freed
        rq.check_and_charge_quota(
            store, self.volume, self.bucket,
            sum(p["size"] for p in listed)
            - (int(old.get("size", 0)) if old else 0),
            (0 if old is not None else 1) + len(markers),
        )
        if markers:
            rq.put_parent_markers(store, self.volume, self.bucket,
                                  markers, mpu["replication"], self.ts)
        # orphaned parts: uploaded but omitted from the complete request
        listed_nos = {str(int(p["part_number"])) for p in self.parts}
        for no, part in mpu["parts"].items():
            if no not in listed_nos:
                _release_blocks(store, part, self.ts, f"{mk}/part{no}")
        if old is not None:
            _release_blocks(store, old, self.ts, kk)
        info = {
            "volume": self.volume,
            "bucket": self.bucket,
            "name": self.key,
            "object_id": self.key_id,
            "replication": mpu["replication"],
            "checksum_type": mpu["checksum_type"],
            "bytes_per_checksum": mpu["bytes_per_checksum"],
            "size": sum(p["size"] for p in listed),
            "block_groups": [g for p in listed for g in p["block_groups"]],
            "etag": _final_etag(listed),
            "created": mpu["created"],
            "modified": self.ts,
        }
        if mpu.get("metadata"):
            info["metadata"] = mpu["metadata"]
        if mpu.get("encryption"):
            info["encryption"] = mpu["encryption"]
            # each part carries its own IV: the reader decrypts the
            # stitched stream segment by segment
            info["enc_parts"] = [
                {"size": p["size"], "iv": p["iv"]} for p in listed
            ]
        store.put("keys", kk, info)
        store.delete("multipart", mk)
        return info


@dataclass
class AbortMultipartUpload(rq.OMRequest):
    volume: str
    bucket: str
    key: str
    upload_id: str
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        mk = mpu_key(self.volume, self.bucket, self.key, self.upload_id)
        mpu = store.get("multipart", mk)
        if mpu is None:
            raise rq.OMError(NO_SUCH_UPLOAD, mk)
        for no, part in mpu["parts"].items():
            _release_blocks(store, part, self.ts, f"{mk}/part{no}")
        store.delete("multipart", mk)


@dataclass
class PurgeExpiredOpenKeys(rq.OMRequest):
    """Drop expired open-key sessions (OpenKeyCleanupService completion).
    Open sessions hold no committed block groups in our flow, so dropping
    the entry is sufficient; any datanode-side chunks of an uncommitted
    block are unreferenced and reclaimed by container scrubbing."""

    entries: list[str] = field(default_factory=list)

    def apply(self, store):
        for k in self.entries:
            store.delete("open_keys", k)


class OpenKeyCleanupService:
    """Scan open-key sessions older than max_age and purge them
    (ozone-manager service/OpenKeyCleanupService analog)."""

    def __init__(self, om, max_age_s: float = 7 * 24 * 3600.0):
        self.om = om
        self.max_age_s = max_age_s

    def run_once(self, limit: int = 256) -> int:
        cutoff = time.time() - self.max_age_s
        expired = []
        hsynced = []
        for k, info in self.om.store.iterate("open_keys"):
            if rq.is_snapmeta(k):
                continue
            if info.get("hsync_client_id"):
                # a live hsync stream refreshes "modified" on every sync:
                # only a writer that stopped syncing for max_age is dead
                if max(info.get("created", 0),
                       info.get("modified", 0)) < cutoff:
                    hsynced.append(info)
            elif info.get("created", 0) < cutoff:
                expired.append(k)
        expired = expired[:limit]
        if expired:
            self.om.submit(PurgeExpiredOpenKeys(expired))
        # an expired hsynced session means the writer died mid-stream:
        # seal the key at its last synced length instead of discarding it
        # (the reference's cleanup commits hsync'd keys the same way)
        for info in hsynced[:limit]:
            try:
                self.om.recover_lease(
                    info["volume"], info["bucket"], info["name"]
                )
            except rq.OMError:
                log.warning("lease recovery failed for %s/%s/%s",
                            info["volume"], info["bucket"], info["name"])
        return len(expired) + len(hsynced[:limit])


class MultipartUploadCleanupService:
    """Abort multipart uploads older than max_age
    (MultipartUploadCleanupService analog): submits the same abort
    request a client would, so part blocks reach the purge chain."""

    def __init__(self, om, max_age_s: float = 7 * 24 * 3600.0):
        self.om = om
        self.max_age_s = max_age_s

    def run_once(self, limit: int = 256) -> int:
        cutoff = time.time() - self.max_age_s
        expired = [
            mpu
            for _, mpu in self.om.store.iterate("multipart")
            if mpu.get("created", 0) < cutoff
        ][:limit]
        for mpu in expired:
            self.om.submit(
                AbortMultipartUpload(
                    mpu["volume"], mpu["bucket"], mpu["name"],
                    mpu["upload_id"],
                )
            )
        return len(expired)
