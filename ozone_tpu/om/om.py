"""OzoneManager: namespace service (volumes/buckets/keys).

Facade mirroring the reference's OzoneManager + KeyManagerImpl surface:
volume/bucket CRUD, open-key sessions with SCM block allocation
(OMKeyCreateRequest.preExecute allocates blocks from SCM), commit, lookup,
list, delete-to-purge-queue, rename. Writes flow through the
request/apply split (om/requests.py) so consensus can be slotted in; reads
bypass it like the reference's submitRequestDirectlyToOM read path
(OzoneManagerProtocolServerSideTranslatorPB.java:198).

The KeyDeletingService analog purges deleted keys: collects their block
groups and issues datanode block deletions via the client factory.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from pathlib import Path
from typing import Any, Optional

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.om import requests as rq
from ozone_tpu.om.metadata import (
    OMMetadataStore,
    bucket_key,
    key_key,
    slab_key,
    volume_key,
)
from ozone_tpu.om.sharding import shardmap as _shardmap
from ozone_tpu.scm.pipeline import ReplicationConfig
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.utils.audit import AuditLogger
from ozone_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


class OpenKeySession:
    def __init__(self, om: "OzoneManager", info: dict, client_id: str):
        self.om = om
        self.volume = info["volume"]
        self.bucket = info["bucket"]
        self.key = info["name"]
        self.client_id = client_id
        self.replication = ReplicationConfig.parse(info["replication"])
        self.checksum_type = info["checksum_type"]
        self.bytes_per_checksum = info["bytes_per_checksum"]
        # FSO sessions carry their resolved tree position
        self.parent_id: Optional[str] = info.get("parent_id")
        self.file_name: Optional[str] = info.get("file_name")
        #: TDE/GDPR envelope bundle the OM minted at open ({} = plain)
        self.encryption: dict = info.get("encryption", {})


class OzoneManager:
    def __init__(
        self,
        db_path: Path,
        scm: StorageContainerManager,
        clients: Optional[DatanodeClientFactory] = None,
        block_size: int = 16 * 1024 * 1024,
    ):
        self.store = OMMetadataStore(Path(db_path))
        self.scm = scm
        self.clients = clients
        self.block_size = block_size
        self.metrics = MetricsRegistry("om")
        self.audit = AuditLogger("om")
        self._lock = threading.RLock()
        # durable upgrade-quiesce marker (OzoneManagerPrepareState):
        # rides the metadata store so a restart is deterministic
        self._prepared = self.store.get("system", "om_prepared") is not None
        # native authorizer (reference ozone.acl.enabled, default off)
        self.acl_enabled = False
        self._authorizer = None
        self._superusers = {"root"}
        self._caller = threading.local()
        # block-token minting (OzoneBlockTokenSecretManager analog,
        # reference hdds.block.token.enabled): installed by the daemon
        # via enable_block_tokens; None = insecure cluster, no tokens
        self.token_issuer = None
        # TDE key authority (OzoneKMSUtil / KMSClientProvider role):
        # master keys live in the replicated store
        from ozone_tpu.utils.kms import KeyProvider

        self.kms = KeyProvider(self.store)
        # delegation-token lifetimes (reference defaults:
        # dfs.container.token renew-interval 1d, max-lifetime 7d)
        self.dtoken_renew_interval_s = 24 * 3600.0
        self.dtoken_max_lifetime_s = 7 * 24 * 3600.0
        self.dtoken_key_lifetime_s = 30 * 24 * 3600.0
        # paged snapshot-diff jobs (SnapshotDiffManager job model)
        from ozone_tpu.om.snapshots import SnapshotDiffJobs

        self._diff_jobs = SnapshotDiffJobs(self)
        # geo-replication shipper (replication_geo/shipper.py):
        # installed by the daemon wiring under HA; created lazily with
        # defaults by run_geo_once on standalone OMs
        self.geo = None
        # lifecycle sweeper (lifecycle/service.py): installed by the
        # daemon under HA (term-fenced on the ring); lazily built with
        # defaults by run_lifecycle_once on standalone OMs
        self.lifecycle = None

    # ----------------------------------------------------------- acl/tenant
    def enable_acls(self, superusers=("root",)) -> None:
        from ozone_tpu.om.acl import NativeAuthorizer

        self._superusers = set(superusers)
        self._authorizer = NativeAuthorizer(self.store, superusers)
        self.acl_enabled = True

    def user_context(self, user: Optional[str], groups=(),
                     via_token: bool = False):
        """Context manager binding the caller identity for ACL checks on
        this thread (gateways and the OM RPC service wrap each request;
        unbound calls run as the local superuser, like the reference's
        in-process trusted callers). ``via_token`` records that the
        identity was authenticated BY a delegation token — such callers
        must not mint further tokens (see get_delegation_token)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            prev = getattr(self._caller, "identity", None)
            self._caller.identity = (user, tuple(groups), bool(via_token))
            try:
                yield
            finally:
                self._caller.identity = prev

        return _ctx()

    def current_user(self) -> tuple[Optional[str], tuple]:
        ident = getattr(self._caller, "identity", None)
        return (ident[0], ident[1]) if ident else (None, ())

    def caller_token_authenticated(self) -> bool:
        ident = getattr(self._caller, "identity", None)
        return bool(ident and len(ident) > 2 and ident[2])

    def caller_identity_bound(self) -> bool:
        """True when a transport layer bound ANY identity for this call
        (even an anonymous one) — distinguishes remote RPCs from
        genuinely in-process trusted callers."""
        return getattr(self._caller, "identity", None) is not None

    def check_access(self, volume: str, bucket: Optional[str],
                     key: Optional[str], right,
                     user: Optional[str] = None, groups=()) -> None:
        """Raise ACLDeniedError (an OMError) unless the caller holds
        `right` (an acl.ACLRight or its name) on the object; no-op with
        ACLs off or with no bound identity."""
        if not self.acl_enabled:
            return
        if user is None:
            user, groups = self.current_user()
        if user is None:
            return
        from ozone_tpu.om.acl import ACLDeniedError, ACLRight

        if isinstance(right, str):
            right = ACLRight[right]
        if not self._authorizer.check(volume, bucket, key, user, groups,
                                      right):
            path = "/".join(x for x in (volume, bucket, key) if x)
            self.metrics.counter("acl_denied").inc()
            raise ACLDeniedError(user, right, path)

    def _check_superuser(self) -> None:
        if not self.acl_enabled:
            return
        user, _ = self.current_user()
        if user is not None and user not in self._superusers:
            from ozone_tpu.om.acl import ACLDeniedError, ACLRight

            raise ACLDeniedError(user, ACLRight.CREATE, "<admin>")

    def modify_acl(self, obj_type: str, volume: str, bucket: str = "",
                   path: str = "", op: str = "add",
                   acls: Optional[list] = None) -> bool:
        """Add/remove/set grants; `acls` items are OzoneAcl, dicts, or
        CLI strings like `user:alice:rwl[DEFAULT]`."""
        from ozone_tpu.om.acl import normalize_acls

        self.check_access(volume, bucket or None,
                          path if (obj_type == "key" and path) else None,
                          "WRITE_ACL")
        return self.submit(rq.ModifyAcl(obj_type, volume, bucket, path,
                                        op, normalize_acls(acls)))

    def get_acls(self, obj_type: str, volume: str, bucket: str = "",
                 path: str = "") -> list[dict]:
        self.check_access(volume, bucket or None,
                          path if (obj_type == "key" and path) else None,
                          "READ_ACL")
        table, k = rq._acl_target(self.store, obj_type, volume, bucket, path)
        row = self.store.get(table, k)
        if row is None:
            if table == "prefixes":
                return []
            raise rq.OMError(rq.KEY_NOT_FOUND if table == "keys" else
                             rq.VOLUME_NOT_FOUND if table == "volumes" else
                             rq.BUCKET_NOT_FOUND, k)
        return row.get("acls", [])

    def create_tenant(self, tenant: str, volume: str = "",
                      owner: str = "root") -> None:
        self._check_superuser()
        self.submit(rq.CreateTenant(tenant, volume, owner))

    def delete_tenant(self, tenant: str) -> None:
        self._check_superuser()
        self.submit(rq.DeleteTenant(tenant))

    def list_tenants(self) -> list[dict]:
        return [t for _, t in self.store.iterate("tenants")]

    def tenant_assign_user(self, tenant: str, user: str,
                           access_id: str = "") -> dict:
        self._check_superuser()
        return self.submit(rq.AssignUserToTenant(tenant, user, access_id))

    def tenant_revoke_access(self, access_id: str) -> None:
        self._check_superuser()
        self.submit(rq.RevokeUserAccessId(access_id))

    def tenant_for_access_id(self, access_id: str) -> Optional[dict]:
        """S3 gateway hook: map an authenticated access id to its tenant
        record (tenant volume = the S3 bucket namespace for the request,
        the reference's OMMultiTenantManager.getTenantVolumeName)."""
        row = self.store.get("tenant_access", access_id)
        if row is None:
            return None
        return self.store.get("tenants", row["tenant"])

    def list_tenant_users(self, tenant: str) -> list[dict]:
        return [r for _, r in self.store.iterate("tenant_access")
                if r["tenant"] == tenant]

    # ----------------------------------------------------------- prepare
    def prepare(self) -> int:
        """Quiesce writes for a coordinated upgrade (`ozone om prepare` /
        OzoneManagerPrepareState analog): flush the double buffer, reject
        further writes until cancel_prepare, return the prepared txid.
        The marker is durable (system table) so restarts stay prepared."""
        with self._lock:
            self.store.put("system", "om_prepared", {"prepared": True})
            self.store.flush()
            self._prepared = True
            return self.store.txid

    def cancel_prepare(self) -> None:
        with self._lock:
            self.store.delete("system", "om_prepared")
            self.store.flush()
            self._prepared = False

    def reload_prepared(self) -> None:
        """Re-read the durable marker (after a snapshot install replaced
        the underlying tables)."""
        with self._lock:
            self._prepared = \
                self.store.get("system", "om_prepared") is not None

    @property
    def prepared(self) -> bool:
        return getattr(self, "_prepared", False)

    # ----------------------------------------------------------- write path
    def check_layout_allowed(self, request_name: str) -> None:
        """Layout-feature request gating (RequestFeatureValidator.java:84
        via RequestValidations.java:108): a request touching a feature
        the cluster has not finalized yet is refused at admission. Runs
        on the leader's preExecute side — followers apply whatever the
        leader admitted, so a mixed ring stays deterministic."""
        from ozone_tpu.utils.upgrade import (
            GATED_OM_REQUESTS,
            PRE_FINALIZE_ERROR,
        )

        feat = GATED_OM_REQUESTS.get(request_name)
        lvm = getattr(self.scm, "layout", None)
        if feat is None or lvm is None:
            return
        if not lvm.is_allowed(feat):
            raise rq.OMError(
                PRE_FINALIZE_ERROR,
                f"{request_name} needs layout feature {feat.name} "
                f"(v{feat.version}); cluster is at layout "
                f"{lvm.metadata_version} — run `admin finalizeupgrade`",
            )

    def upgrade_status(self) -> dict:
        """Cluster finalization view (UpgradeFinalizer.status analog),
        served over the OM protocol so gateways can gate their own
        feature paths (see S3 aws-chunked)."""
        fin = getattr(self.scm, "finalizer", None)
        if fin is None:
            from ozone_tpu.utils.upgrade import FEATURES, LATEST_VERSION

            return {
                "metadata_version": LATEST_VERSION,
                "software_version": LATEST_VERSION,
                "needs_finalization": False,
                "features": [
                    {"name": f.name, "version": f.version, "allowed": True}
                    for f in FEATURES
                ],
            }
        return fin.status()

    def submit(self, request: rq.OMRequest) -> Any:
        """preExecute on the leader, then apply (the future Raft boundary
        sits between the two)."""
        self.check_layout_allowed(type(request).__name__)
        if self.prepared:
            raise rq.OMError(
                "OM_PREPARED",
                "OM is prepared for upgrade; writes are rejected until "
                "cancelprepare")
        from ozone_tpu.utils.tracing import Tracer

        with self.metrics.timer(request.audit_action).time(), \
                Tracer.instance().span("om:submit",
                                       request=type(request).__name__):
            request.pre_execute(self)
            with self._lock:
                if self.prepared:
                    # re-check under the lock: a write that passed the
                    # fast-path check must not apply after prepare()'s
                    # flush point (the quiesce would be a lie)
                    raise rq.OMError(
                        "OM_PREPARED",
                        "OM is prepared for upgrade; writes are rejected "
                        "until cancelprepare")
                try:
                    # atomic: one request's rows are never split across
                    # durable batches (metadata.OMMetadataStore.atomic)
                    with self.store.atomic():
                        result = request.apply(self.store)
                except rq.OMError as e:
                    self.audit.log(request.audit_action, vars(request),
                                   ok=False, error=e.code)
                    raise
            # durable before ack: the reference's double buffer
            # completes client futures only after the RocksDB batch
            # lands (OzoneManagerDoubleBuffer.flushTransactions:293) —
            # an acked mutation must survive a crash. GROUP commit,
            # outside the apply lock: concurrent submits share one
            # sqlite commit (one fsync), the double buffer's batching.
            self.store.flush_group()
            self.audit.log(request.audit_action, vars(request), ok=True)
            self.metrics.counter("write_ops").inc()
            return result

    # ----------------------------------------------------------- volumes
    def create_volume(self, volume: str, owner: str = "root") -> None:
        self._check_superuser()
        self.submit(rq.CreateVolume(volume, owner))

    def delete_volume(self, volume: str) -> None:
        self._check_superuser()
        self.submit(rq.DeleteVolume(volume))

    def set_volume_owner(self, volume: str, owner: str) -> dict:
        """ozone sh volume update --user analog; only the current owner
        or a superuser may transfer ownership."""
        user, _ = self.current_user()
        if self.acl_enabled and user is not None:
            info = self.volume_info(volume)
            if user != info.get("owner") and user not in self._superusers:
                raise rq.OMError(
                    rq.PERMISSION_DENIED,
                    f"{user!r} is neither the owner nor a superuser")
        return self.submit(rq.SetVolumeOwner(volume, owner))

    def volume_info(self, volume: str) -> dict:
        v = self.store.get("volumes", volume_key(volume))
        if v is None:
            raise rq.OMError(rq.VOLUME_NOT_FOUND, volume)
        return v

    def list_volumes(self) -> list[dict]:
        return [v for _, v in self.store.iterate("volumes")]

    # ----------------------------------------------------------- buckets
    def create_bucket(
        self, volume: str, bucket: str, replication: str = "rs-6-3-1024k",
        layout: str = "OBJECT_STORE", encryption_key: str = "",
        gdpr: bool = False,
    ) -> None:
        self.check_access(volume, None, None, "CREATE")
        self.check_shard(volume, bucket)
        # fail fast on a bad scheme string (unknown codec family, bad
        # LRC geometry) instead of storing it and erroring at first put
        ReplicationConfig.parse(replication)
        self.submit(rq.CreateBucket(volume, bucket, replication, layout,
                                    encryption_key=encryption_key,
                                    gdpr=gdpr))

    def create_bucket_link(self, src_volume: str, src_bucket: str,
                           volume: str, bucket: str) -> None:
        """Create a link bucket aliasing src (ozone sh bucket link).
        On a sharded plane, a link whose source hashes to ANOTHER shard
        must instead go through the cross-shard 2PC
        (sharding/txn.link_bucket_cross) — this single-ring path gates
        on the link's own shard and validates the source locally."""
        self.check_access(volume, None, None, "CREATE")
        self.check_shard(volume, bucket)
        self.submit(rq.CreateBucket(
            volume, bucket,
            source_volume=src_volume, source_bucket=src_bucket,
        ))

    def check_shard(self, volume: str, bucket: str) -> None:
        """Shard-ownership gate (sharding/shardmap.py): raises
        SHARD_MOVED when this replica's replicated shard config does
        not own the (volume, bucket) slot. A no-op (one cached `system`
        row get) on unsharded deployments."""
        _shardmap.check_shard(self.store, volume, bucket)

    def resolve_bucket(self, volume: str, bucket: str) -> tuple[str, str]:
        """Follow link-bucket chains to the real bucket (reference
        OmBucketInfo source resolution): raises DANGLING_LINK when a
        link's source is missing or the chain loops."""
        seen = set()
        while True:
            self.check_shard(volume, bucket)
            row = self.store.get("buckets", bucket_key(volume, bucket))
            if row is None:
                if seen:  # we got here by following a link
                    raise rq.OMError(rq.DANGLING_LINK,
                                     f"{volume}/{bucket} missing")
                raise rq.OMError(rq.BUCKET_NOT_FOUND, f"{volume}/{bucket}")
            src = row.get("source")
            if not src:
                return volume, bucket
            if (volume, bucket) in seen:
                raise rq.OMError(rq.DANGLING_LINK,
                                 f"link loop at {volume}/{bucket}")
            seen.add((volume, bucket))
            volume, bucket = src["volume"], src["bucket"]

    def delete_bucket(self, volume: str, bucket: str) -> None:
        self.check_access(volume, bucket, None, "DELETE")
        self.submit(rq.DeleteBucket(volume, bucket))

    def bucket_info(self, volume: str, bucket: str) -> dict:
        b = self.store.get("buckets", bucket_key(volume, bucket))
        if b is None:
            raise rq.OMError(rq.BUCKET_NOT_FOUND, f"{volume}/{bucket}")
        if b.get("source"):
            # a link reports its own identity but the SOURCE's effective
            # replication/layout (that is where keys live)
            rv, rb = self.resolve_bucket(volume, bucket)
            eff = self.store.get("buckets", bucket_key(rv, rb)) or {}
            b = dict(b)
            b["replication"] = eff.get("replication", b["replication"])
            b["layout"] = eff.get("layout", b["layout"])
        return b

    def list_buckets(self, volume: str) -> list[dict]:
        return [
            b for _, b in self.store.iterate("buckets", volume_key(volume) + "/")
        ]

    # ----------------------------------------------------------- keys
    def _is_fso(self, binfo: dict) -> bool:
        return binfo.get("layout") == "FILE_SYSTEM_OPTIMIZED"

    @staticmethod
    def _is_legacy(binfo: dict) -> bool:
        return binfo.get("layout") == "LEGACY"

    # ------------------------------------------------------------- TDE/KMS
    def _mint_encryption(self, binfo: dict) -> dict:
        """Per-key envelope bundle for an encrypted or GDPR bucket
        (generateEncryptedKey at open; rides the replicated OpenKey so
        every replica stores the same bundle)."""
        import os as _os

        if binfo.get("encryption_key"):
            return self.kms.generate_edek(binfo["encryption_key"])
        if binfo.get("gdpr"):
            return {"gdpr_secret": _os.urandom(32).hex(),
                    "iv": _os.urandom(16).hex()}
        return {}

    def kms_create_key(self, name: str, rotate: bool = False) -> dict:
        self._check_superuser()  # key authority ops are admin-only
        return self.submit(rq.CreateMasterKey(name, rotate=rotate))

    def kms_key_info(self, name: str) -> dict:
        return self.kms.master_info(name)

    def kms_list_keys(self) -> list[str]:
        return self.kms.master_key_names()

    def kms_decrypt(self, volume: str, bucket: str,
                    bundle: dict) -> str:
        """EDEK -> DEK for an authorized reader/writer. The bundle must
        belong to THIS bucket (its master key must be the bucket's
        configured key) — otherwise READ on any bucket would unwrap any
        bucket's EDEKs (confused-deputy). Writers qualify too: the open
        path hands them a fresh EDEK they must be able to use."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        binfo = self.bucket_info(volume, bucket)
        if binfo.get("encryption_key") != bundle.get("master"):
            raise rq.OMError(
                rq.PERMISSION_DENIED,
                "EDEK was not issued for this bucket's master key")
        try:
            self.check_access(volume, bucket, None, "READ")
        except rq.OMError:
            self.check_access(volume, bucket, None, "WRITE")
        return self.kms.unwrap_edek(bundle).hex()

    def open_key(
        self,
        volume: str,
        bucket: str,
        key: str,
        replication: Optional[str] = None,
        metadata: Optional[dict] = None,
        acls: Optional[list] = None,
    ) -> OpenKeySession:
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "CREATE")
        binfo = self.bucket_info(volume, bucket)
        repl = replication or binfo["replication"]
        if replication:
            # per-key override audit (same fail-fast as create_bucket /
            # set_bucket_replication): a bad scheme string must refuse
            # the PUT with a typed error BEFORE the open lands a ring
            # entry — not explode in the session constructor and leave
            # an orphaned open_keys row behind
            try:
                ReplicationConfig.parse(replication)
            except rq.OMError:
                raise
            except Exception as e:
                raise rq.OMError(
                    rq.INVALID_REQUEST,
                    f"bad per-key replication {replication!r}: {e}")
        client_id = uuid.uuid4().hex[:16]
        enc = self._mint_encryption(binfo)
        if self._is_fso(binfo):
            req = fso.OpenFile(volume, bucket, key, client_id, repl,
                               metadata=metadata or {}, encryption=enc,
                               acls=acls or [])
            parent = self.submit(req)
            name = fso.split_path(key)[-1]
            open_k = f"{fso.dir_key(volume, bucket, parent, name)}/{client_id}"
        else:
            legacy = self._is_legacy(binfo)
            if legacy:
                key = rq.normalize_fs_path(key)
            req = rq.OpenKey(volume, bucket, key, client_id, repl,
                             metadata=metadata or {}, fs_paths=legacy,
                             encryption=enc, acls=acls or [])
            self.submit(req)
            open_k = f"{key_key(volume, bucket, key)}/{client_id}"
        info = self.store.get("open_keys", open_k)
        self.metrics.counter("keys_opened").inc()
        return OpenKeySession(self, info, client_id)

    def enable_block_tokens(self, issuer) -> None:
        """Install the token issuer (hdds.block.token.enabled=true):
        every allocation carries WRITE capability tokens, every lookup
        re-mints fresh READ tokens, and the OM's own datanode traffic
        (key-deletion, lease recovery) self-signs via the shared store."""
        self.token_issuer = issuer
        if self.clients is not None:
            self.clients.tokens.issuer = issuer

    def grant_write_tokens(self, g: BlockGroup) -> BlockGroup:
        """Attach capability tokens to a fresh allocation (the token in
        the reference's AllocatedBlock). READ is included so the writer
        can probe committed lengths on its own blocks (lease recovery)."""
        if self.token_issuer is not None:
            from ozone_tpu.utils.security import AccessMode

            owner = self.current_user()[0] or "client"
            g.token = self.token_issuer.issue(
                g.block_id, [AccessMode.READ, AccessMode.WRITE], owner=owner)
            g.container_token = self.token_issuer.issue_container(
                g.container_id, owner=owner)
        return g

    def mint_read_tokens(self, info: dict) -> dict:
        """Fresh READ tokens on a lookup result's block groups (the
        reference mints block tokens in KeyManagerImpl lookup; stored
        key info never holds tokens)."""
        if self.token_issuer is None or not info.get("block_groups"):
            return info
        from ozone_tpu.storage.ids import BlockID
        from ozone_tpu.utils.security import AccessMode

        owner = self.current_user()[0] or "client"
        info = dict(info)
        groups = []
        for g in info["block_groups"]:
            g = dict(g)
            bid = BlockID(int(g["container_id"]), int(g["local_id"]))
            g["token"] = self.token_issuer.issue(
                bid, [AccessMode.READ], owner=owner)
            groups.append(g)
        info["block_groups"] = groups
        return info

    def allocate_block(
        self, session: OpenKeySession, excluded: Optional[list[str]] = None,
        excluded_containers: Optional[list[int]] = None,
    ) -> BlockGroup:
        """SCM block allocation for an open key (ScmBlockLocationProtocol
        .allocateBlock analog)."""
        return self.grant_write_tokens(self.scm.allocate_block(
            session.replication, self.block_size, excluded,
            excluded_containers,
        ))

    def commit_key(
        self, session: OpenKeySession, groups: list[BlockGroup], size: int,
        hsync: bool = False,
    ) -> None:
        from ozone_tpu.om import fso

        fence = getattr(session, "expect_object_id", "")
        fence_gen = int(getattr(session, "expect_generation", -1))
        if session.parent_id is not None:
            self.submit(
                fso.CommitFile(
                    session.volume,
                    session.bucket,
                    session.parent_id,
                    session.file_name,
                    session.client_id,
                    size,
                    [g.to_json() for g in groups],
                    hsync=hsync,
                    expect_object_id=fence,
                    expect_generation=fence_gen,
                )
            )
        else:
            self.submit(
                rq.CommitKey(
                    session.volume,
                    session.bucket,
                    session.key,
                    session.client_id,
                    size,
                    [g.to_json() for g in groups],
                    replication=str(session.replication),
                    hsync=hsync,
                    expect_object_id=fence,
                    expect_generation=fence_gen,
                )
            )
        self.metrics.counter("keys_hsynced" if hsync
                             else "keys_committed").inc()

    def hsync_key(
        self, session: OpenKeySession, groups: list[BlockGroup], size: int
    ) -> None:
        """Mid-write durability commit: the key becomes readable at the
        synced length while the write stream stays open (the reference's
        hsync support in KeyOutputStream / OMKeyCommitRequest isHsync)."""
        self.commit_key(session, groups, size, hsync=True)

    def list_open_files(self, volume: str = "", bucket: str = "",
                        prefix: str = "", start_after: str = "",
                        limit: int = 100) -> dict:
        """Page through open write sessions (reference:
        OzoneManager.listOpenFiles:3233 over the openKeyTable, surfaced
        by `ozone admin om list-open-files`): every un-committed open
        key with its client id, size so far, timestamps and whether an
        hsync lease holder exists. `start_after` is the previous page's
        `continuation` value."""
        if limit is None or limit <= 0:
            raise rq.OMError(rq.INVALID_REQUEST,
                             f"limit must be positive, got {limit}")
        if volume and bucket:
            volume, bucket = self.resolve_bucket(volume, bucket)
        # push the scan window into the store: both OBS (key_key) and FSO
        # (dir_key) open rows share the /volume[/bucket]/ key prefix,
        # which also excludes the /.snapmeta/ rows when a volume is given
        base = ""
        if volume:
            base = (f"/{volume}/{bucket}/" if bucket else f"/{volume}/")
        entries: list[dict] = []
        truncated = False
        cursor = start_after
        while not truncated:
            chunk = self.store.iterate_range("open_keys", base, cursor,
                                             limit + 1)
            for ok, info in chunk:
                cursor = ok
                if rq.is_snapmeta(ok):
                    continue  # snapshot chain metadata rides this table
                if volume and info.get("volume") != volume:
                    continue
                if bucket and info.get("bucket") != bucket:
                    continue
                if prefix and not info.get("name", "").startswith(prefix):
                    continue
                if len(entries) >= limit:
                    truncated = True
                    break
                entries.append({
                    "open_key": ok,
                    "volume": info.get("volume"),
                    "bucket": info.get("bucket"),
                    "key": info.get("name"),
                    "client_id": ok.rsplit("/", 1)[-1],
                    "size": info.get("size", 0),
                    "created": info.get("created"),
                    "modified": info.get("modified"),
                    "hsync": bool(info.get("hsync_client_id")),
                })
            if len(chunk) < limit + 1:
                break  # scan exhausted
        return {
            "open_files": entries,
            "truncated": truncated,
            "continuation": (entries[-1]["open_key"]
                             if truncated and entries else ""),
        }

    def recover_lease(self, volume: str, bucket: str, key: str) -> dict:
        """Seal an abandoned hsynced write and fence its dead writer
        (recoverLease of the ozonefs adapter / OMRecoverLeaseRequest)."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        out = self.submit(rq.RecoverLease(volume, bucket, key))
        self.metrics.counter("leases_recovered").inc()
        return out

    def set_quota(self, volume: str, bucket: str = "",
                  quota_bytes: Optional[int] = None,
                  quota_namespace: Optional[int] = None) -> dict:
        """Space/namespace quota on a volume or bucket; None leaves a
        dimension unchanged, -1 clears it to unlimited. Setting quota
        through a link targets the source (where usage is charged)."""
        if bucket:
            volume, bucket = self.resolve_bucket(volume, bucket)
        return self.submit(rq.SetQuota(volume, bucket,
                                       quota_bytes, quota_namespace))

    def repair_quota(self, volume: str, page: int = 1000) -> dict:
        """Recompute usage counters from the key/file tables — the
        QuotaRepairTask analog. The recount pages through the tables
        OUTSIDE the ring's apply lock (``iterate_range`` windows of
        `page` rows), then replicates only per-bucket DELTAS through
        one small ``ApplyQuotaRepair`` — a repair of a huge namespace
        never stalls concurrent writers (round-4 verdict: the old
        apply scanned every key under the ring's write lock)."""
        vk = volume_key(volume)
        if self.store.get("volumes", vk) is None:
            raise rq.OMError(rq.VOLUME_NOT_FOUND, volume)
        deltas: dict[str, list[int]] = {}
        for bk, brow in list(self.store.iterate("buckets", f"/{volume}/")):
            used = keys = 0
            for table in ("keys", "files"):
                after = ""
                while True:
                    rows = self.store.iterate_range(
                        table, f"{bk}/", start_after=after, limit=page)
                    for k, info in rows:
                        used += int(info.get("size", 0))
                        keys += 1
                    if len(rows) < page:
                        break
                    after = rows[-1][0]
            deltas[bk] = [used - int(brow.get("used_bytes", 0)),
                          keys - int(brow.get("key_count", 0))]
        return self.submit(rq.ApplyQuotaRepair(volume, deltas))

    # ------------------------------------------------------------ snapshots
    def _snapshots(self):
        from ozone_tpu.om.snapshots import SnapshotManager

        return SnapshotManager(self)

    def create_snapshot(self, volume: str, bucket: str, name: str) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self._snapshots().create_snapshot(volume, bucket,
                                                 name).to_json()

    def list_snapshots(self, volume: str, bucket: str) -> list[dict]:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return [s.to_json()
                for s in self._snapshots().list_snapshots(volume, bucket)]

    def snapshot_info(self, volume: str, bucket: str, name: str) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self._snapshots().get_snapshot(volume, bucket,
                                              name).to_json()

    def delete_snapshot(self, volume: str, bucket: str, name: str) -> None:
        volume, bucket = self.resolve_bucket(volume, bucket)
        self._snapshots().delete_snapshot(volume, bucket, name)

    def rename_snapshot(self, volume: str, bucket: str, name: str,
                        new_name: str) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self.submit(rq.RenameSnapshot(volume, bucket, name,
                                             new_name))

    def snapshot_diff(self, volume: str, bucket: str, from_snapshot: str,
                      to_snapshot=None) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self._snapshots().snapshot_diff(volume, bucket,
                                               from_snapshot, to_snapshot)

    def snapshot_diff_submit(self, volume: str, bucket: str,
                             from_snapshot: str,
                             to_snapshot: Optional[str] = None) -> dict:
        """Submit (or poll) a paged diff job — SnapshotDiffManager's
        job model; page results with snapshot_diff_page."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "LIST")
        return self._diff_jobs.submit(volume, bucket, from_snapshot,
                                      to_snapshot)

    def snapshot_diff_page(self, job_id: str, token: str = "",
                           page_size: int = 1000) -> dict:
        out = self._diff_jobs.page(job_id, token, page_size)
        # the page names keys: same LIST right as the submit path
        self.check_access(out["volume"], out["bucket"], None, "LIST")
        return out

    def snapshot_keys(self, volume: str, bucket: str, name: str) -> list[dict]:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self._snapshots().list_keys(volume, bucket, name)

    def snapshot_lookup_key(self, volume: str, bucket: str, name: str,
                            key: str) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self.mint_read_tokens(
            self._snapshots().lookup_key(volume, bucket, name, key))

    def lookup_key(self, volume: str, bucket: str, key: str) -> dict:
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, key, "READ")

        binfo = self.bucket_info(volume, bucket)
        if self._is_fso(binfo):
            info = fso.lookup_file(self.store, volume, bucket, key)
        else:
            if self._is_legacy(binfo):
                key = rq.normalize_fs_path(key)
            info = self.store.get("keys", key_key(volume, bucket, key))
        if info is None:
            raise rq.OMError(rq.KEY_NOT_FOUND, f"{volume}/{bucket}/{key}")
        self.metrics.counter("key_lookups").inc()
        info = self._join_needle(volume, bucket, info)
        return self.mint_read_tokens(info)

    def _join_needle(self, volume: str, bucket: str, info: dict) -> dict:
        """Attach the slab's block groups to a needle key's lookup
        result: needle rows store only (slab, offset, length, crc) —
        the tiny-object metadata economy — and the one extra store get
        here is what buys it. The read path then slices the needle out
        of the slab with ordinary ranged group reads."""
        nd = info.get("needle")
        if not nd:
            return info
        srow = self.store.get(
            "slabs", slab_key(volume, bucket, nd["slab"]))
        if srow is None:
            raise rq.OMError(
                "SLAB_NOT_FOUND",
                f"slab {nd['slab']} missing for "
                f"{volume}/{bucket}/{info.get('name')}")
        info = dict(info)
        info["block_groups"] = srow["block_groups"]
        return info

    def key_block_groups(self, info: dict) -> list[BlockGroup]:
        """Materialize BlockGroup objects (with pipelines) from key info."""
        out = []
        for g in info["block_groups"]:
            out.append(BlockGroup.from_json(g))
        return out

    def list_keys(self, volume: str, bucket: str, prefix: str = "",
                  start_after: str = "",
                  limit: Optional[int] = None) -> list[dict]:
        """Keys of a bucket, name-ordered, optionally resuming after
        `start_after` and capped at `limit` (the reference's paged
        listKeys(startKey, maxKeys)). OBS buckets page with a bounded
        store scan; FSO buckets page with a pruned lexicographic tree
        walk — neither materializes the whole namespace per page."""
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "LIST")
        binfo = self.bucket_info(volume, bucket)  # raises BUCKET_NOT_FOUND
        if self._is_fso(binfo):
            return fso.walk_files_paged(
                self.store, volume, bucket, prefix=prefix,
                start_after=start_after,
                limit=None if limit is None else max(0, int(limit)),
            )
        base = bucket_key(volume, bucket) + "/"
        floor = (base + start_after) if start_after else ""
        return [
            k
            for _, k in self.store.iterate_range(
                "keys", base + prefix, start_after=floor,
                limit=None if limit is None else max(0, int(limit)),
            )
        ]

    def delete_key(self, volume: str, bucket: str, key: str,
                   expect_object_id: str = "") -> None:
        """Delete a key. ``expect_object_id`` ("" = unfenced, the user
        API's latest-version semantics) makes the delete conditional on
        the live row still being that version — background replayers
        (geo replication, lifecycle expiry) fence so a concurrent
        overwrite always wins with KEY_MODIFIED."""
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, key, "DELETE")
        binfo = self.bucket_info(volume, bucket)
        if self._is_fso(binfo):
            if expect_object_id:
                raise rq.OMError(
                    rq.INVALID_REQUEST,
                    "fenced deletes are not supported on "
                    "FILE_SYSTEM_OPTIMIZED buckets")
            self.submit(fso.DeleteFile(volume, bucket, key))
        else:
            if self._is_legacy(binfo):
                key = rq.normalize_fs_path(key)
            self.submit(rq.DeleteKey(volume, bucket, key,
                                     expect_object_id=expect_object_id))
        self.metrics.counter("keys_deleted").inc()

    def rename_key(self, volume: str, bucket: str, key: str, new_key: str) -> None:
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, key, "WRITE")
        binfo = self.bucket_info(volume, bucket)
        if self._is_fso(binfo):
            self.submit(fso.RenameEntry(volume, bucket, key, new_key))
        else:
            legacy = self._is_legacy(binfo)
            if legacy:
                key = rq.normalize_fs_path(key)
                new_key = rq.normalize_fs_path(new_key)
            self.submit(rq.RenameKey(volume, bucket, key, new_key,
                                     fs_paths=legacy))

    def set_key_attrs(self, volume: str, bucket: str, key: str,
                      attrs: dict, preconds: Optional[dict] = None
                      ) -> dict:
        """Merge filesystem attributes (owner/group/permission/mtime/
        atime) onto a key, file, or directory (the HttpFS SETOWNER /
        SETPERMISSION / SETTIMES verbs; reference KeyManagerImpl
        setattr paths). None values delete attributes; `preconds` maps
        attr -> must-exist bool, checked atomically in the apply (the
        xattr CREATE/REPLACE flags)."""
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, key, "WRITE")
        if self._is_fso(self.bucket_info(volume, bucket)):
            return self.submit(fso.SetEntryAttrs(volume, bucket, key,
                                                 attrs, preconds or {}))
        return self.submit(rq.SetKeyAttrs(volume, bucket, key, attrs,
                                          preconds or {}))

    def set_bucket_attrs(self, volume: str, bucket: str,
                         attrs: dict) -> dict:
        """Filesystem attrs on the bucket itself (ofs exposes buckets
        as directories; chmod on /volume/bucket lands here)."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        return self.submit(rq.SetBucketAttrs(volume, bucket, attrs))

    # ----------------------------------------------------- s3 secrets / acl
    def get_s3_secret(self, access_id: str, create: bool = True) -> Optional[str]:
        """Fetch (creating on first use, like the reference's
        S3GetSecretRequest) the SigV4 secret for an access id."""
        row = self.store.get("s3_secrets", access_id)
        if row is not None:
            return row["secret"]
        if not create:
            return None
        import secrets as _secrets

        return self.submit(
            rq.SetS3Secret(access_id, _secrets.token_hex(32), if_absent=True)
        )

    def revoke_s3_secret(self, access_id: str) -> None:
        self.submit(rq.RevokeS3Secret(access_id))

    # ----------------------------------------------------- delegation tokens
    def get_delegation_token(self, renewer: str,
                             owner: Optional[str] = None) -> dict:
        """Issue a signed delegation token for the current caller
        (OzoneManager.getDelegationToken → OMGetDelegationTokenRequest).
        Returns the portable token dict (identifier + sig)."""
        from ozone_tpu.om import dtokens
        import secrets as _secrets

        if self.caller_token_authenticated():
            # a token holder chaining fresh tokens would defeat max_date:
            # the reference refuses issuing a delegation token to a
            # caller that authenticated WITH one (Hadoop
            # AbstractDelegationTokenSecretManager)
            raise rq.OMError(
                rq.TOKEN_ERROR,
                "delegation token cannot be issued to a caller "
                "authenticated by a delegation token")
        user, _ = self.current_user()
        owner = owner or user or "root"
        key = dtokens.current_key(self.store)
        if key is None:
            self.submit(rq.NewDTokenMasterKey())
            key = dtokens.current_key(self.store)
        now = time.time()
        ident = {
            "owner": owner,
            "renewer": renewer,
            "real_user": user or owner,
            "issue": round(now, 3),
            "max_date": round(now + self.dtoken_max_lifetime_s, 3),
            "token_id": _secrets.token_hex(8),
            "key_id": key["key_id"],
        }
        ident["sig"] = dtokens.sign(bytes.fromhex(key["material"]), ident)
        expiry = round(min(now + self.dtoken_renew_interval_s,
                           ident["max_date"]), 3)
        self.submit(rq.StoreDelegationToken(ident, expiry))
        return ident

    def renew_delegation_token(self, token: dict) -> float:
        """Extend the renewable expiry; only the named renewer may renew
        (the caller identity is checked inside the replicated request).
        The renewer-substitution fallback is restricted to genuinely
        in-process callers (no transport identity bound at all): a
        remote RPC that reached us WITHOUT an authenticated identity is
        refused instead of silently acting as the token's renewer —
        otherwise any anonymous holder of the token file could renew to
        max_date (advisor finding, round 3)."""
        from ozone_tpu.om import dtokens

        try:
            dtokens.check_signature(self.store, token)
        except dtokens.DTokenError as e:
            raise rq.OMError(rq.TOKEN_ERROR, e.msg)
        user, _ = self.current_user()
        if user is None and self.caller_identity_bound():
            raise rq.OMError(
                rq.TOKEN_ERROR,
                "renewing a delegation token requires an authenticated "
                "caller identity")
        return self.submit(rq.RenewDelegationToken(
            str(token["token_id"]), user or str(token["renewer"])))

    def cancel_delegation_token(self, token: dict) -> None:
        from ozone_tpu.om import dtokens

        try:
            dtokens.check_signature(self.store, token)
        except dtokens.DTokenError as e:
            raise rq.OMError(rq.TOKEN_ERROR, e.msg)
        user, _ = self.current_user()
        if user is None and self.caller_identity_bound():
            # same rule as renew: anonymous remote callers cannot cancel
            raise rq.OMError(
                rq.TOKEN_ERROR,
                "cancelling a delegation token requires an "
                "authenticated caller identity")
        self.submit(rq.CancelDelegationToken(
            str(token["token_id"]), user or str(token["owner"])))

    def verify_delegation_token(self, token: dict) -> dict:
        """Authenticate a presented token: returns the stored row (the
        authoritative owner/renewer) or raises OMError(TOKEN_ERROR)."""
        from ozone_tpu.om import dtokens

        try:
            return dtokens.verify(self.store, token)
        except dtokens.DTokenError as e:
            raise rq.OMError(rq.TOKEN_ERROR, e.msg)

    def run_dtoken_cleanup_once(self) -> int:
        """Purge expired tokens + orphaned master keys (the reference's
        ExpiredTokenRemover sweep)."""
        return self.submit(rq.PurgeExpiredDTokens())

    def set_bucket_acl(self, volume: str, bucket: str,
                       acl: list[dict]) -> None:
        self.submit(rq.SetBucketAcl(volume, bucket, acl))

    def set_bucket_replication(self, volume: str, bucket: str,
                               replication: str) -> dict:
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        ReplicationConfig.parse(replication)  # same fail-fast as create
        return self.submit(
            rq.SetBucketReplication(volume, bucket, replication))

    # ----------------------------------------------------- small objects
    def set_bucket_smallobj(self, volume: str, bucket: str,
                            enabled: bool = True, inline_max: int = 0,
                            needle_max: int = 0) -> dict:
        """Opt a bucket into (or out of) the small-object path.
        Eligibility (flat layout, no encryption) is validated in the
        replicated apply — config time, the parse-time analog — so an
        ineligible combination fails with a typed error up front."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        return self.submit(rq.SetBucketSmallObj(
            volume, bucket, enabled=enabled,
            inline_max=int(inline_max), needle_max=int(needle_max)))

    def smallobj_conf(self, binfo: dict) -> Optional[dict]:
        """Effective inline/needle thresholds for a bucket, or None when
        the bucket never opted in. Stored zeros defer to the env knobs
        (OZONE_TPU_INLINE_MAX / OZONE_TPU_NEEDLE_MAX) at read time, so
        an operator can retune a fleet without touching bucket rows."""
        so = binfo.get("smallobj")
        if not so:
            return None
        from ozone_tpu.utils.config import env_int

        inline_max = int(so.get("inline_max", 0)) or env_int(
            "OZONE_TPU_INLINE_MAX", 4096)
        needle_max = int(so.get("needle_max", 0)) or env_int(
            "OZONE_TPU_NEEDLE_MAX", 256 * 1024)
        return {"inline_max": inline_max,
                "needle_max": max(needle_max, inline_max)}

    def put_inline_key(self, volume: str, bucket: str, key: str,
                       data: bytes, metadata: Optional[dict] = None
                       ) -> dict:
        """Tiny-object PUT in ONE ring entry (no open session, no
        blocks): the value rides the replicated key row. Size is gated
        against the bucket's inline threshold here, on the leader, so a
        raft entry can never be bloated past the configured bound."""
        import base64

        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "CREATE")
        binfo = self.bucket_info(volume, bucket)
        conf = self.smallobj_conf(binfo)
        if conf is None:
            raise rq.OMError(
                rq.SMALLOBJ_NOT_SUPPORTED,
                f"{volume}/{bucket} has no small-object config")
        raw = bytes(data)
        if len(raw) > conf["inline_max"]:
            raise rq.OMError(
                rq.INVALID_REQUEST,
                f"{len(raw)} bytes exceeds inline_max "
                f"{conf['inline_max']}")
        info = self.submit(rq.PutInlineKey(
            volume, bucket, key, base64.b64encode(raw).decode("ascii"),
            len(raw), metadata or {}))
        from ozone_tpu.client.slab import METRICS as SMALLOBJ

        SMALLOBJ.counter("inline_puts").inc()
        SMALLOBJ.counter("inline_bytes").inc(len(raw))
        return info

    def commit_keys(self, volume: str, bucket: str, slab: dict,
                    entries: list[dict]) -> dict:
        """Batched needle commit: N keys + the sealed slab directory in
        ONE ring entry (the raft-amortization half of the tiny-object
        fast path; the packer flush and freon mass ingestion both land
        here). Per-entry rewrite fences are honored individually —
        see rq.CommitKeys."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "CREATE")
        out = self.submit(rq.CommitKeys(volume, bucket, slab=slab,
                                        entries=list(entries)))
        from ozone_tpu.client.slab import METRICS as SMALLOBJ

        SMALLOBJ.counter("commit_batches").inc()
        SMALLOBJ.counter("needles_committed").inc(
            len(out.get("committed", ())))
        return out

    def allocate_slab_group(self, replication: str,
                            excluded: Optional[list[str]] = None,
                            excluded_containers: Optional[list[int]]
                            = None) -> BlockGroup:
        """SCM allocation for a slab block (no open-key session: slabs
        are not keys). Same token grant as allocate_block."""
        return self.grant_write_tokens(self.scm.allocate_block(
            ReplicationConfig.parse(replication), self.block_size,
            excluded, excluded_containers))

    def slab_info(self, volume: str, bucket: str, slab_id: str) -> dict:
        row = self.store.get("slabs", slab_key(volume, bucket, slab_id))
        if row is None:
            raise rq.OMError(rq.KEY_NOT_FOUND, f"slab {slab_id}")
        return row

    def list_slabs(self, volume: str, bucket: str) -> list[dict]:
        return [v for _, v in self.store.iterate(
            "slabs", bucket_key(volume, bucket) + "/")]

    def run_slab_compaction_once(self, max_slabs: Optional[int] = None
                                 ) -> dict:
        """Trigger one needle-compaction sweep (dead-ratio scan +
        survivor rewrite + old-slab release). Rides the lifecycle
        service so the daemon deployment gets the same term fencing."""
        if getattr(self, "lifecycle", None) is None:
            from ozone_tpu.lifecycle.service import LifecycleService

            self.lifecycle = LifecycleService(self, clients=self.clients)
        return self.lifecycle.compact_slabs_once(max_slabs=max_slabs)

    def get_bucket_acl(self, volume: str, bucket: str) -> list[dict]:
        return self.bucket_info(volume, bucket).get("acl", [])

    # ----------------------------------------------------- bucket lifecycle
    def set_bucket_lifecycle(self, volume: str, bucket: str,
                             rules: list[dict]) -> dict:
        """Install per-bucket lifecycle rules (S3
        PutBucketLifecycleConfiguration analog): prefix + age_days +
        action (TRANSITION_TO_EC(scheme) | EXPIRE), persisted in bucket
        metadata through the replicated ring (lifecycle/policy.py)."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        return self.submit(rq.SetBucketLifecycle(volume, bucket, rules))

    def get_bucket_lifecycle(self, volume: str, bucket: str) -> list[dict]:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self.bucket_info(volume, bucket).get("lifecycle", [])

    def delete_bucket_lifecycle(self, volume: str, bucket: str) -> None:
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        self.submit(rq.DeleteBucketLifecycle(volume, bucket))

    def lifecycle_status(self) -> dict:
        """Sweeper state (fencing term, cursor, last stats) + live
        counters — the `lifecycle status` CLI / Recon panel view."""
        from ozone_tpu.utils.metrics import get_registry

        row = self.store.get("system", "lifecycle_state") or {}
        reg = get_registry("lifecycle")
        return {
            "term": row.get("term"),
            "cursor": row.get("cursor") or {},
            "stats": row.get("stats") or {},
            "in_progress": bool(row.get("cursor")),
            "metrics": reg.snapshot() if reg is not None else {},
        }

    def run_lifecycle_once(self, max_keys: Optional[int] = None) -> dict:
        """Trigger one lifecycle sweep (the `lifecycle run-now` verb).
        Uses the daemon-installed service when present (term-fenced on
        the HA ring); standalone OMs get a local default service."""
        if getattr(self, "lifecycle", None) is None:
            from ozone_tpu.lifecycle.service import LifecycleService

            self.lifecycle = LifecycleService(self, clients=self.clients)
        return self.lifecycle.run_once(max_keys=max_keys)

    # ------------------------------------------------- geo replication (DR)
    def set_bucket_geo_replication(self, volume: str, bucket: str,
                                   rules: list[dict]) -> dict:
        """Install per-bucket cross-cluster replication rules (S3
        PutBucketReplication analog): prefix + destination cluster
        endpoint + optional destination bucket/scheme, persisted in
        bucket metadata through the replicated ring
        (replication_geo/rules.py)."""
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        return self.submit(
            rq.SetBucketGeoReplication(volume, bucket, rules))

    def get_bucket_geo_replication(self, volume: str,
                                   bucket: str) -> list[dict]:
        volume, bucket = self.resolve_bucket(volume, bucket)
        return self.bucket_info(volume, bucket).get("geo_replication", [])

    def delete_bucket_geo_replication(self, volume: str,
                                      bucket: str) -> None:
        volume, bucket = self.resolve_bucket(volume, bucket)
        self.check_access(volume, bucket, None, "WRITE")
        self.submit(rq.DeleteBucketGeoReplication(volume, bucket))

    def geo_status(self) -> dict:
        """Shipper state (fencing term, WAL cursor, last stats) + live
        counters and WAL-head lag — the `replication status` CLI /
        Recon panel view."""
        from ozone_tpu.utils.metrics import get_registry

        row = self.store.get("system", "geo_state") or {}
        reg = get_registry("replication")
        out = {
            "term": row.get("term"),
            "cursor": row.get("cursor") or {},
            "bootstrapped": row.get("bootstrapped") or [],
            "stats": row.get("stats") or {},
            "metrics": reg.snapshot() if reg is not None else {},
        }
        if getattr(self, "geo", None) is not None:
            out["lag"] = self.geo.lag()
        return out

    def run_geo_once(self, max_entries: Optional[int] = None) -> dict:
        """Trigger one replication ship cycle (the `replication
        run-now` verb). Uses the daemon-installed shipper when present
        (term-fenced on the HA ring); standalone OMs get a local
        default shipper."""
        if getattr(self, "geo", None) is None:
            from ozone_tpu.replication_geo.shipper import (
                ReplicationShipper,
            )

            self.geo = ReplicationShipper(self, clients=self.clients)
        return self.geo.run_once(max_entries=max_entries)

    # ----------------------------------------------------- multipart upload
    def initiate_multipart_upload(
        self, volume: str, bucket: str, key: str,
        replication: Optional[str] = None,
        metadata: Optional[dict] = None,
    ) -> str:
        from ozone_tpu.om import multipart as mpu

        volume, bucket = self.resolve_bucket(volume, bucket)
        binfo = self.bucket_info(volume, bucket)
        legacy = self._is_legacy(binfo)
        if legacy:
            key = rq.normalize_fs_path(key)
        if replication:
            # same per-key override audit as open_key: typed refusal
            # before any ring entry, never mid-upload
            try:
                ReplicationConfig.parse(replication)
            except rq.OMError:
                raise
            except Exception as e:
                raise rq.OMError(
                    rq.INVALID_REQUEST,
                    f"bad per-key replication {replication!r}: {e}")
        return self.submit(
            mpu.InitiateMultipartUpload(
                volume, bucket, key, replication=replication or "",
                metadata=metadata or {}, fs_paths=legacy,
                encryption=self._mint_encryption(binfo),
            )
        )

    def multipart_info(
        self, volume: str, bucket: str, key: str, upload_id: str
    ) -> dict:
        from ozone_tpu.om import multipart as mpu

        volume, bucket = self.resolve_bucket(volume, bucket)
        if self._is_legacy(self.bucket_info(volume, bucket)):
            key = rq.normalize_fs_path(key)
        info = self.store.get(
            "multipart", mpu.mpu_key(volume, bucket, key, upload_id)
        )
        if info is None:
            raise rq.OMError(
                mpu.NO_SUCH_UPLOAD, f"{volume}/{bucket}/{key}/{upload_id}"
            )
        return info

    def open_multipart_part(
        self, volume: str, bucket: str, key: str, upload_id: str
    ) -> OpenKeySession:
        """Session for writing one part's blocks (createMultipartKey,
        RpcClient.java:2009): same datapath as a normal key write; the
        part is recorded by commit_multipart_part."""
        info = self.multipart_info(volume, bucket, key, upload_id)
        return OpenKeySession(self, info, client_id=upload_id)

    def commit_multipart_part(
        self,
        session: OpenKeySession,
        part_number: int,
        groups: list[BlockGroup],
        size: int,
        etag: str,
        iv: str = "",
    ) -> str:
        from ozone_tpu.om import multipart as mpu

        return self.submit(
            mpu.CommitMultipartPart(
                session.volume,
                session.bucket,
                session.key,
                session.client_id,
                part_number,
                size,
                etag,
                [g.to_json() for g in groups],
                iv=iv,
            )
        )

    def complete_multipart_upload(
        self, volume: str, bucket: str, key: str, upload_id: str,
        parts: list[dict],
    ) -> dict:
        from ozone_tpu.om import multipart as mpu

        volume, bucket = self.resolve_bucket(volume, bucket)
        legacy = self._is_legacy(self.bucket_info(volume, bucket))
        if legacy:
            key = rq.normalize_fs_path(key)
        return self.submit(
            mpu.CompleteMultipartUpload(volume, bucket, key, upload_id,
                                        parts, fs_paths=legacy)
        )

    def abort_multipart_upload(
        self, volume: str, bucket: str, key: str, upload_id: str
    ) -> None:
        from ozone_tpu.om import multipart as mpu

        volume, bucket = self.resolve_bucket(volume, bucket)
        if self._is_legacy(self.bucket_info(volume, bucket)):
            key = rq.normalize_fs_path(key)
        self.submit(mpu.AbortMultipartUpload(volume, bucket, key, upload_id))

    def list_parts(
        self, volume: str, bucket: str, key: str, upload_id: str
    ) -> list[dict]:
        info = self.multipart_info(volume, bucket, key, upload_id)
        return sorted(
            info["parts"].values(), key=lambda p: p["part_number"]
        )

    def list_multipart_uploads(
        self, volume: str, bucket: str, prefix: str = ""
    ) -> list[dict]:
        volume, bucket = self.resolve_bucket(volume, bucket)
        base = bucket_key(volume, bucket) + "/"
        return [
            m for _, m in self.store.iterate("multipart", base + prefix)
        ]

    def run_open_key_cleanup_once(
        self, max_age_s: float = 7 * 24 * 3600.0, limit: int = 256
    ) -> int:
        from ozone_tpu.om import multipart as mpu

        return mpu.OpenKeyCleanupService(self, max_age_s).run_once(limit)

    def run_mpu_cleanup_once(
        self, max_age_s: float = 7 * 24 * 3600.0, limit: int = 256
    ) -> int:
        from ozone_tpu.om import multipart as mpu

        return mpu.MultipartUploadCleanupService(self, max_age_s).run_once(
            limit
        )

    # ----------------------------------------------------- FSO file system
    def create_directory(self, volume: str, bucket: str, path: str) -> None:
        from ozone_tpu.om import fso

        volume, bucket = self._require_fso(volume, bucket)
        self.submit(fso.CreateDirectory(volume, bucket, path))

    def _require_fso(self, volume: str, bucket: str) -> tuple[str, str]:
        """Resolve links, then demand an FSO layout; returns the REAL
        (volume, bucket) so directory ops act on the source tree."""
        from ozone_tpu.om import fso

        volume, bucket = self.resolve_bucket(volume, bucket)
        if not self._is_fso(self.bucket_info(volume, bucket)):
            raise rq.OMError(fso.NOT_A_DIRECTORY,
                             f"{volume}/{bucket} is not an FSO bucket")
        return volume, bucket

    def delete_directory(
        self, volume: str, bucket: str, path: str, recursive: bool = False
    ) -> None:
        from ozone_tpu.om import fso

        volume, bucket = self._require_fso(volume, bucket)
        self.submit(fso.DeleteDirectory(volume, bucket, path, recursive))

    def get_file_status(self, volume: str, bucket: str, path: str) -> dict:
        from ozone_tpu.om import fso

        volume, bucket = self._require_fso(volume, bucket)
        return fso.get_status(self.store, volume, bucket, path)

    def list_status(self, volume: str, bucket: str, path: str) -> list[dict]:
        from ozone_tpu.om import fso

        volume, bucket = self._require_fso(volume, bucket)
        return fso.list_status(self.store, volume, bucket, path)

    def run_dir_deleting_service_once(self, limit: int = 256) -> int:
        from ozone_tpu.om import fso

        return fso.DirectoryDeletingService(self).run_once(limit)

    # ----------------------------------------------------------- services
    def run_key_deleting_service_once(self, limit: int = 100) -> int:
        """Purge deleted keys: hand their blocks to the SCM deletion log
        (which drives datanode deletes over heartbeats — the reference's
        KeyDeletingService -> SCM DeletedBlockLog chain), then drop the
        entries. Returns keys purged."""
        entries = list(self.store.iterate("deleted_keys"))[:limit]
        if not entries:
            return 0
        from ozone_tpu.storage.ids import BlockID

        purged: list[str] = []
        txs: list[tuple] = []
        dead_needles: dict[tuple, list[int]] = {}
        for dk, info in entries:
            # defer-delete for snapshotted buckets: block data may still be
            # referenced by a snapshot (reference: snapshot deferred
            # deletion via SnapshotDeletingService/SstFilteringService)
            vol, bkt = info.get("volume"), info.get("bucket")
            if vol and bkt and next(
                self.store.iterate("open_keys",
                                   rq.snapmeta_key(vol, bkt, "")),
                None,
            ):
                continue
            nd = info.get("needle")
            if nd:
                # a needle's blocks are the SHARED slab's — never handed
                # to SCM here; its death is accounted on the slab row so
                # the compaction sweep can see the dead ratio grow
                acc = dead_needles.setdefault(
                    (vol, bkt, nd["slab"]), [0, 0])
                acc[0] += 1
                acc[1] += int(nd.get("length", info.get("size", 0)))
                purged.append(dk)
                continue
            for g in info.get("block_groups", []):
                txs.append(
                    (BlockID(g["container_id"], g["local_id"]),
                     list(g["nodes"]))
                )
            purged.append(dk)
        if txs:
            self.scm.delete_blocks(txs)
        for (vol, bkt, sid), (count, nbytes) in dead_needles.items():
            self.submit(rq.AccountDeadNeedles(vol, bkt, sid,
                                              count, nbytes))
        self.submit(rq.PurgeDeletedKeys(purged))
        return len(purged)

    def close(self) -> None:
        self.store.close()
