"""OM write requests: the preExecute / apply split.

Mirrors the reference's OMClientRequest command pattern (ozone-manager
request/OMClientRequest.java:114 preExecute — leader-side normalization and
resource allocation — and :143 validateAndUpdateCache — the deterministic
state mutation applied on every OM replica). Keeping the split means a
consensus layer (Raft) can be inserted later by shipping the post-
preExecute request through a log without rewriting any request logic
(SURVEY.md section 7 step 5).

Each request implements:
  pre_execute(om)  -> may talk to SCM, assign ids/timestamps; returns None
  apply(store)     -> pure function of (request, store); idempotent-safe
  audit fields     -> for the audit log
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ozone_tpu.om.metadata import (
    OMMetadataStore,
    bucket_key,
    key_key,
    slab_key,
    volume_key,
)


class OMError(Exception):
    def __init__(self, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}" if msg else code)
        self.code = code
        self.msg = msg  # bare message for re-wrapping without code stacking


VOLUME_NOT_FOUND = "VOLUME_NOT_FOUND"
VOLUME_ALREADY_EXISTS = "VOLUME_ALREADY_EXISTS"
VOLUME_NOT_EMPTY = "VOLUME_NOT_EMPTY"
BUCKET_NOT_FOUND = "BUCKET_NOT_FOUND"
BUCKET_ALREADY_EXISTS = "BUCKET_ALREADY_EXISTS"
BUCKET_NOT_EMPTY = "BUCKET_NOT_EMPTY"
KEY_NOT_FOUND = "KEY_NOT_FOUND"
KEY_MODIFIED = "KEY_MODIFIED"
DANGLING_LINK = "DANGLING_LINK"


_REQUEST_TYPES: dict[str, type] = {}


@dataclass
class OMRequest:
    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _REQUEST_TYPES[cls.__name__] = cls

    def pre_execute(self, om: Any) -> None:  # noqa: D401
        """Leader-side phase; default no-op."""

    def apply(self, store: OMMetadataStore) -> Any:
        raise NotImplementedError

    @property
    def audit_action(self) -> str:
        return type(self).__name__

    def to_json(self) -> dict:
        """Wire form for the replicated log (post-preExecute state, so
        followers apply deterministically without re-running preExecute —
        the OMClientRequest contract)."""
        import dataclasses

        return {"type": type(self).__name__, **dataclasses.asdict(self)}

    @staticmethod
    def from_json(d: dict) -> "OMRequest":
        d = dict(d)
        cls = _REQUEST_TYPES[d.pop("type")]
        return cls(**d)


@dataclass
class CreateVolume(OMRequest):
    volume: str
    owner: str = "root"
    quota_bytes: int = -1
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()

    def apply(self, store):
        k = volume_key(self.volume)
        if store.exists("volumes", k):
            raise OMError(VOLUME_ALREADY_EXISTS, self.volume)
        store.put(
            "volumes",
            k,
            {
                "name": self.volume,
                "owner": self.owner,
                "quota_bytes": self.quota_bytes,
                "created": self.created,
            },
        )


@dataclass
class DeleteVolume(OMRequest):
    volume: str

    def apply(self, store):
        k = volume_key(self.volume)
        if not store.exists("volumes", k):
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        if next(store.iterate("buckets", k + "/"), None) is not None:
            raise OMError(VOLUME_NOT_EMPTY, self.volume)
        store.delete("volumes", k)


@dataclass
class CreateBucket(OMRequest):
    """Create a bucket — or, with source_volume/source_bucket set, a
    LINK bucket (ozone sh bucket link analog): a named alias whose key
    operations resolve to the source bucket."""

    volume: str
    bucket: str
    replication: str = "rs-6-3-1024k"
    layout: str = "OBJECT_STORE"
    versioning: bool = False
    created: float = 0.0
    source_volume: str = ""
    source_bucket: str = ""
    #: TDE: name of the KMS master key every key in this bucket gets an
    #: EDEK under (BucketEncryptionKeyInfo analog); "" = plaintext
    encryption_key: str = ""
    #: GDPR right-to-erasure: per-key plaintext secret destroyed in the
    #: same apply that deletes the key (crypto-erasure)
    gdpr: bool = False

    def pre_execute(self, om) -> None:
        self.created = time.time()
        if self.encryption_key:
            # fail fast at create, not at first write
            om.kms.master_info(self.encryption_key)

    #: the reference's three bucket layouts
    #: (BucketLayoutAwareOMKeyRequestFactory): OBS = flat object table,
    #: FSO = directory tree tables, LEGACY = flat table with filesystem
    #: path semantics (normalization, parent markers, conflict checks)
    LAYOUTS = ("OBJECT_STORE", "FILE_SYSTEM_OPTIMIZED", "LEGACY")

    def apply(self, store):
        from ozone_tpu.om.acl import inherit_defaults

        if self.layout not in self.LAYOUTS:
            raise OMError(INVALID_REQUEST,
                          f"unknown bucket layout {self.layout!r}")
        vrow = store.get("volumes", volume_key(self.volume))
        if vrow is None:
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        k = bucket_key(self.volume, self.bucket)
        if store.exists("buckets", k):
            raise OMError(BUCKET_ALREADY_EXISTS, k)
        row = {
            "volume": self.volume,
            "name": self.bucket,
            "replication": self.replication,
            "layout": self.layout,
            "versioning": self.versioning,
            "created": self.created,
            # DEFAULT grants on the volume flow down as ACCESS grants
            # (OzoneAclUtil.inheritDefaultAcls)
            "acls": inherit_defaults(vrow.get("acls", [])),
        }
        if self.encryption_key:
            row["encryption_key"] = self.encryption_key
        if self.gdpr:
            row["gdpr"] = True
        if self.source_volume and self.source_bucket:
            # links may be created before their source (reference
            # semantics: dangling links resolve lazily and error on use)
            row["source"] = {
                "volume": self.source_volume,
                "bucket": self.source_bucket,
            }
        store.put("buckets", k, row)


@dataclass
class DeleteBucket(OMRequest):
    volume: str
    bucket: str

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        if not store.exists("buckets", k):
            raise OMError(BUCKET_NOT_FOUND, k)
        # FSO buckets keep their namespace in dirs/files, not keys; a
        # detached-but-unpurged subtree still counts as non-empty
        for table in ("keys", "files", "dirs", "deleted_dirs"):
            if next(store.iterate(table, k + "/"), None) is not None:
                raise OMError(BUCKET_NOT_EMPTY, k)
        store.delete("buckets", k)


QUOTA_EXCEEDED = "QUOTA_EXCEEDED"


def check_and_charge_quota(
    store, volume: str, bucket: str, bytes_delta: int, keys_delta: int
) -> None:
    """Enforce volume/bucket space + namespace quotas on growth, then
    update the usage counters (the reference's usedBytes/usedNamespace
    accounting on OmBucketInfo/OmVolumeArgs; quota checked in the key
    commit path). quota_bytes / quota_namespace of -1 mean unlimited."""
    bk = bucket_key(volume, bucket)
    vk = volume_key(volume)
    brow = store.get("buckets", bk)
    vrow = store.get("volumes", vk)
    if bytes_delta > 0 or keys_delta > 0:
        if brow is not None:
            bq = int(brow.get("quota_bytes", -1))
            used = int(brow.get("used_bytes", 0))
            if bq >= 0 and used + bytes_delta > bq:
                raise OMError(
                    QUOTA_EXCEEDED,
                    f"bucket {bk}: {used} + {bytes_delta} > quota {bq}",
                )
            nq = int(brow.get("quota_namespace", -1))
            kc = int(brow.get("key_count", 0))
            if nq >= 0 and kc + keys_delta > nq:
                raise OMError(
                    QUOTA_EXCEEDED,
                    f"bucket {bk}: {kc + keys_delta} keys > quota {nq}",
                )
        if vrow is not None:
            vq = int(vrow.get("quota_bytes", -1))
            vused = int(vrow.get("used_bytes", 0))
            if vq >= 0 and vused + bytes_delta > vq:
                raise OMError(
                    QUOTA_EXCEEDED,
                    f"volume /{volume}: {vused} + {bytes_delta} > "
                    f"quota {vq}",
                )
            vnq = int(vrow.get("quota_namespace", -1))
            vkc = int(vrow.get("key_count", 0))
            if vnq >= 0 and vkc + keys_delta > vnq:
                raise OMError(
                    QUOTA_EXCEEDED,
                    f"volume /{volume}: {vkc + keys_delta} keys > "
                    f"quota {vnq}",
                )
    if brow is not None:
        brow["used_bytes"] = max(
            0, int(brow.get("used_bytes", 0)) + bytes_delta)
        brow["key_count"] = max(
            0, int(brow.get("key_count", 0)) + keys_delta)
        store.put("buckets", bk, brow)
    if vrow is not None:
        vrow["used_bytes"] = max(
            0, int(vrow.get("used_bytes", 0)) + bytes_delta)
        vrow["key_count"] = max(
            0, int(vrow.get("key_count", 0)) + keys_delta)
        store.put("volumes", vk, vrow)


def direct_sessions_of(store, ek: str) -> list[str]:
    """Open-session storage keys belonging to entry `ek` itself — NOT to
    longer key names that extend it with a slash (OBS key names legally
    contain slashes; client ids never do)."""
    return [
        k
        for k, _ in store.iterate("open_keys", f"{ek}/")
        if "/" not in k[len(ek) + 1:]
    ]


def finalize_commit(store, table: str, ek: str, info: dict, old,
                    client_id: str, hsync: bool, modified: float) -> None:
    """Shared hsync-aware commit tail for OBS keys and FSO files: stamp or
    clear hsync_client_id, keep or drop the open session, and route a
    superseded previous version to the purge chain — fencing its writer
    first if that version was a live hsync stream (its blocks are about to
    be purged, so its eventual commit must fail rather than resurrect
    them). Quota is enforced before any mutation: the space delta is the
    new size minus whatever the previous version already charged."""
    _, vol, bkt = ek.split("/", 3)[:3]
    # COW snapshots: capture the pre-overwrite image first
    if table == "keys":
        preserve_preimage(store, vol, bkt, ek)
    elif table == "files":
        preserve_fso_preimage(store, vol, bkt, "files", ek)
    check_and_charge_quota(
        store, vol, bkt,
        int(info.get("size", 0)) - (int(old.get("size", 0)) if old else 0),
        0 if old is not None else 1,
    )
    # per-commit generation (OmKeyInfo updateID): bumps on EVERY commit
    # of the row — including hsyncs, which reuse the session's object_id
    # — so the rewrite fence can detect any intervening commit
    info["generation"] = (int(old.get("generation", 0)) + 1
                          if old is not None else 1)
    if hsync:
        info["hsync_client_id"] = client_id
        store.put("open_keys", f"{ek}/{client_id}", info)  # session lives on
    else:
        info.pop("hsync_client_id", None)
        store.delete("open_keys", f"{ek}/{client_id}")
    if (
        old is not None
        and (old.get("block_groups") or old.get("needle"))
        and old.get("hsync_client_id") != client_id
    ):
        stale_writer = old.get("hsync_client_id")
        if stale_writer:
            store.delete("open_keys", f"{ek}/{stale_writer}")
        # overwrites are deletions of the old version: its GDPR secret
        # must die here, not linger in the purge chain
        erase_gdpr_secret(old)
        store.put("deleted_keys", f"{ek}:{modified}", old)
    store.put(table, ek, info)


@dataclass
class CommitKey(OMRequest):
    """Finalize a key: move open-key session state into the key table
    (OMKeyCommitRequest analog). With hsync=True this is the mid-write
    durability commit (OMKeyCommitRequest's isHsync path): the key becomes
    visible at the synced length, but the open session survives so the
    writer can keep appending; the key carries hsync_client_id until the
    final commit or a lease recovery clears it."""

    volume: str
    bucket: str
    key: str
    client_id: str
    size: int
    block_groups: list[dict] = field(default_factory=list)
    replication: str = ""
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    modified: float = 0.0
    hsync: bool = False
    #: rewrite fence (ozone sh key rewrite / OmKeyArgs expectedGeneration):
    #: commit only if the live key row still carries this object id —
    #: a concurrent overwrite aborts the rewrite instead of clobbering it
    expect_object_id: str = ""
    #: companion generation fence (-1 = object-id only): catches commits
    #: that keep the object id, e.g. hsyncs of the same open session
    expect_generation: int = -1

    def pre_execute(self, om) -> None:
        self.modified = time.time()

    def apply(self, store):
        kk = key_key(self.volume, self.bucket, self.key)
        open_k = f"{kk}/{self.client_id}"
        if not store.exists("open_keys", open_k):
            raise OMError(KEY_NOT_FOUND, f"no open session {open_k}")
        info = store.get("open_keys", open_k)
        info.update(
            {
                "size": self.size,
                "block_groups": self.block_groups,
                "modified": self.modified,
            }
        )
        if "acls" not in info:
            from ozone_tpu.om.acl import inherit_defaults

            b = store.get("buckets", bucket_key(self.volume, self.bucket))
            if b is not None:
                info["acls"] = inherit_defaults(b.get("acls", []))
        fs_paths = info.pop("fs_paths", False)
        if fs_paths:
            # LEGACY layout: materialize missing parent directory
            # markers (quota-charged) BEFORE the key commit so a quota
            # refusal leaves at worst empty directories, never a key
            # whose parents are missing (OMKeyCommitRequest creates
            # missing parents when filesystem paths are enabled)
            markers = missing_parent_markers(store, self.volume,
                                             self.bucket, self.key)
            if markers:
                check_and_charge_quota(store, self.volume, self.bucket,
                                       0, len(markers))
                put_parent_markers(store, self.volume, self.bucket,
                                   markers, self.replication,
                                   self.modified)
        old = store.get("keys", kk)
        check_rewrite_fence(store, self.expect_object_id, old, open_k,
                            kk, info, self.modified,
                            self.expect_generation)
        finalize_commit(store, "keys", kk, info, old, self.client_id,
                        self.hsync, self.modified)
        return info


def check_rewrite_fence(store, expect_object_id: str, old, open_k: str,
                        row_key: str, info: dict, modified: float,
                        expect_generation: int = -1) -> None:
    """Rewrite-fence enforcement shared by the OBS and FSO commits: when
    the fence is set and the live row no longer carries the expected
    object id AND generation (the per-commit counter finalize_commit
    bumps — object id alone misses hsync commits of the same session,
    the reference fences on generation/updateID for the same reason),
    hand the freshly-written blocks to the deletion chain so they don't
    leak, then refuse the commit."""
    if not expect_object_id:
        return
    if (old is not None
            and old.get("object_id") == expect_object_id
            and (expect_generation < 0
                 or int(old.get("generation", 0)) == expect_generation)):
        return
    store.delete("open_keys", open_k)
    erase_gdpr_secret(info)
    store.put("deleted_keys", f"{row_key}:{modified}", info)
    raise OMError(KEY_MODIFIED,
                  f"{row_key} changed during rewrite; new data discarded")


# ----------------------------------------------------- small objects

SMALLOBJ_NOT_SUPPORTED = "SMALLOBJ_NOT_SUPPORTED"


def check_smallobj_bucket(b: dict) -> None:
    """Small-object eligibility gate, shared by the config verb and the
    replicated applies: inline values and needle-in-slab packing are an
    OBS/LEGACY flat-table feature. FSO buckets keep their namespace in
    the parent-id-keyed file tree (a needle commit bypassing OpenFile
    would skip parent materialization), and encrypted/GDPR buckets need
    a per-key DEK minted at open — neither fits a batched commit that
    never opens a session. Refused with a TYPED error at the
    deterministic boundary (config time / PUT time), never mid-flush."""
    if b.get("layout") == "FILE_SYSTEM_OPTIMIZED":
        raise OMError(
            SMALLOBJ_NOT_SUPPORTED,
            f"/{b.get('volume')}/{b.get('name')} is FILE_SYSTEM_OPTIMIZED"
            " — inline/needle packing needs a flat key table")
    if b.get("encryption_key") or b.get("gdpr"):
        raise OMError(
            SMALLOBJ_NOT_SUPPORTED,
            f"/{b.get('volume')}/{b.get('name')} is encrypted — small-"
            "object commits mint no per-key DEK")
    if b.get("source"):
        raise OMError(
            SMALLOBJ_NOT_SUPPORTED,
            "configure small objects on the link SOURCE bucket")


@dataclass
class SetBucketSmallObj(OMRequest):
    """Opt a bucket into the small-object path (the f4 'warm volume'
    designation): keys at or under `inline_max` bytes are stored inline
    in OM metadata, keys at or under `needle_max` ride the slab packer.
    Eligibility is validated here — config time — so an ineligible
    combination (FSO + packing) fails deterministically up front."""

    volume: str
    bucket: str
    enabled: bool = True
    inline_max: int = 0
    needle_max: int = 0

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        if not self.enabled:
            b.pop("smallobj", None)
        else:
            check_smallobj_bucket(b)
            # zeros defer to the env-knob defaults at read time
            if self.inline_max and self.needle_max and \
                    self.inline_max > self.needle_max:
                raise OMError(
                    INVALID_REQUEST,
                    f"inline_max {self.inline_max} > needle_max "
                    f"{self.needle_max}")
            b["smallobj"] = {"inline_max": int(self.inline_max),
                             "needle_max": int(self.needle_max)}
        store.put("buckets", k, b)
        return b


@dataclass
class PutInlineKey(OMRequest):
    """Tiny-object PUT as ONE ring entry: open + data + commit fused,
    the value riding the key row itself (base64). Zero datapath hops,
    zero blocks — a GET is served straight from OM metadata, including
    lease-gated follower reads. The Haystack insight at its limit: when
    the value is smaller than the per-key fixed costs, the metadata
    write IS the data write."""

    volume: str
    bucket: str
    key: str
    data: str = ""  # base64; bounded by the bucket's inline_max
    size: int = 0
    metadata: dict = field(default_factory=dict)
    modified: float = 0.0
    key_id: str = ""
    #: rewrite fence, same contract as CommitKey (compaction/rewrite
    #: callers): "" = plain overwrite semantics
    expect_object_id: str = ""
    expect_generation: int = -1

    def pre_execute(self, om) -> None:
        import uuid

        self.modified = time.time()
        self.key_id = uuid.uuid4().hex[:16]

    def apply(self, store):
        b = store.get("buckets", bucket_key(self.volume, self.bucket))
        if b is None:
            raise OMError(BUCKET_NOT_FOUND,
                          f"{self.volume}/{self.bucket}")
        check_smallobj_bucket(b)  # replica-deterministic: bucket row
        kk = key_key(self.volume, self.bucket, self.key)
        old = store.get("keys", kk)
        if self.expect_object_id and not (
                old is not None
                and old.get("object_id") == self.expect_object_id
                and (self.expect_generation < 0
                     or int(old.get("generation", 0))
                     == self.expect_generation)):
            raise OMError(KEY_MODIFIED,
                          f"{kk} changed during inline rewrite")
        from ozone_tpu.om.acl import inherit_defaults

        info = {
            "volume": self.volume,
            "bucket": self.bucket,
            "name": self.key,
            "object_id": self.key_id,
            "replication": "inline",
            "checksum_type": "CRC32C",
            "size": int(self.size),
            "block_groups": [],
            "inline": self.data,
            "created": self.modified,
            "modified": self.modified,
            "acls": inherit_defaults(b.get("acls", [])),
        }
        if self.metadata:
            info["metadata"] = dict(self.metadata)
        finalize_commit(store, "keys", kk, info, old, "", False,
                        self.modified)
        return info


@dataclass
class CommitKeys(OMRequest):
    """Batched multi-key needle commit: N tiny keys land in ONE ring
    entry, each recorded as a needle (slab_id, offset, length, crc)
    into a freshly sealed slab whose EC block groups ride the same
    apply. Per-key rewrite fencing is preserved — a fenced entry whose
    live row moved is SKIPPED (its needle bytes turn dead in this slab
    immediately) rather than aborting the batch. The batch itself is
    all-or-nothing: every precondition (bucket, slab uniqueness,
    aggregate quota) is validated before the first mutation, because
    the store's atomic() defers flushes but does not roll back."""

    volume: str
    bucket: str
    slab: dict = field(default_factory=dict)
    entries: list = field(default_factory=list)
    modified: float = 0.0
    key_ids: list = field(default_factory=list)

    def pre_execute(self, om) -> None:
        import uuid

        self.modified = time.time()
        self.key_ids = [uuid.uuid4().hex[:16] for _ in self.entries]

    def apply(self, store):  # noqa: C901 - one validate+mutate pass
        bk = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", bk)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, bk)
        check_smallobj_bucket(b)
        sid = self.slab.get("slab_id", "")
        if not sid or not self.slab.get("block_groups"):
            raise OMError(INVALID_REQUEST, "slab id/groups missing")
        sk = slab_key(self.volume, self.bucket, sid)
        if store.exists("slabs", sk):
            raise OMError(INVALID_REQUEST,
                          f"slab {sid} already sealed")
        # -- validation pass: fences + aggregate quota, NO mutation --
        last: dict = {}  # key -> entry index (duplicate puts: last wins)
        for i, e in enumerate(self.entries):
            last[e["key"]] = i
        live, skipped = [], []
        dead_bytes, bytes_delta, keys_delta = 0, 0, 0
        for i, e in enumerate(self.entries):
            key = e["key"]
            if last[key] != i:
                skipped.append(key)  # superseded within the batch
                dead_bytes += int(e["length"])
                continue
            old = store.get("keys",
                            key_key(self.volume, self.bucket, key))
            fence = e.get("expect_object_id", "")
            gen = int(e.get("expect_generation", -1))
            if fence and not (
                    old is not None
                    and old.get("object_id") == fence
                    and (gen < 0
                         or int(old.get("generation", 0)) == gen)):
                skipped.append(key)  # fenced out: needle bytes go dead
                dead_bytes += int(e["length"])
                continue
            bytes_delta += int(e["length"]) - (
                int(old.get("size", 0)) if old is not None else 0)
            keys_delta += 0 if old is not None else 1
            live.append((i, e, old))
        check_and_charge_quota(store, self.volume, self.bucket,
                               bytes_delta, keys_delta)
        # -- mutation pass: cannot fail past this point ---------------
        from ozone_tpu.om.acl import inherit_defaults

        default_acls = inherit_defaults(b.get("acls", []))
        needles: dict = {}
        committed = []
        for i, e, old in live:
            key = e["key"]
            kk = key_key(self.volume, self.bucket, key)
            info = {
                "volume": self.volume,
                "bucket": self.bucket,
                "name": key,
                "object_id": self.key_ids[i],
                "replication": self.slab.get("replication", ""),
                "checksum_type": "CRC32C",
                "size": int(e["length"]),
                "block_groups": [],
                "needle": {"slab": sid, "offset": int(e["offset"]),
                           "length": int(e["length"]),
                           "crc": int(e["crc"])},
                "created": self.modified,
                "modified": self.modified,
                "acls": e.get("acls") or default_acls,
            }
            if e.get("metadata"):
                info["metadata"] = dict(e["metadata"])
            preserve_preimage(store, self.volume, self.bucket, kk)
            info["generation"] = (int(old.get("generation", 0)) + 1
                                  if old is not None else 1)
            if old is not None and (old.get("block_groups")
                                    or old.get("needle")):
                stale_writer = old.get("hsync_client_id")
                if stale_writer:
                    store.delete("open_keys", f"{kk}/{stale_writer}")
                erase_gdpr_secret(old)
                store.put("deleted_keys", f"{kk}:{self.modified}", old)
            store.put("keys", kk, info)
            needles[key] = {"off": int(e["offset"]),
                            "len": int(e["length"]),
                            "oid": self.key_ids[i]}
            committed.append(key)
        store.put("slabs", sk, {
            "slab_id": sid,
            "volume": self.volume,
            "bucket": self.bucket,
            "replication": self.slab.get("replication", ""),
            "length": int(self.slab.get("length", 0)),
            "block_groups": list(self.slab.get("block_groups", [])),
            "needles": needles,
            "dead_bytes": dead_bytes,
            "dead_count": len(skipped),
            "created": self.modified,
        })
        return {"slab_id": sid, "committed": committed,
                "skipped": skipped}


@dataclass
class AccountDeadNeedles(OMRequest):
    """Dead-needle bookkeeping: a purged key version that lived as a
    needle hands its bytes back to its slab's dead counters (the purge
    chain must NOT hand the shared slab blocks to SCM — other needles
    still live there). Idempotent against a retired slab: accounting
    against a missing row is a no-op."""

    volume: str
    bucket: str
    slab_id: str
    count: int = 0
    nbytes: int = 0

    def apply(self, store):
        sk = slab_key(self.volume, self.bucket, self.slab_id)
        row = store.get("slabs", sk)
        if row is None:
            return None  # slab already compacted away
        row["dead_count"] = int(row.get("dead_count", 0)) + self.count
        row["dead_bytes"] = int(row.get("dead_bytes", 0)) + self.nbytes
        store.put("slabs", sk, row)
        return row


@dataclass
class RetireSlab(OMRequest):
    """Drop a fully-compacted slab's directory row. The caller releases
    the slab's blocks to scm/block_deletion AFTER this commit acks —
    blocks outliving metadata is safe (the scrubber reaps), metadata
    outliving blocks is data loss."""

    volume: str
    bucket: str
    slab_id: str

    def apply(self, store):
        sk = slab_key(self.volume, self.bucket, self.slab_id)
        row = store.get("slabs", sk)
        if row is None:
            raise OMError(KEY_NOT_FOUND, f"slab {sk}")
        store.delete("slabs", sk)
        return row


def snap_prefix(volume: str, bucket: str, snap_id: str) -> str:
    """Key-table prefix holding a snapshot's materialized rows — the ONE
    definition of the layout, shared by the write side (requests) and
    read side (snapshots.py) so they cannot drift."""
    return f"/.snapshot/{volume}/{bucket}/{snap_id}"


def snapmeta_key(volume: str, bucket: str, name: str) -> str:
    """open_keys row carrying a snapshot's chain metadata."""
    return f"/.snapmeta/{volume}/{bucket}/{name}"


#: overlay row meaning "this key did NOT exist when the snapshot was
#: taken" (a key created after a COW snapshot must not leak into its
#: reads through the live-table fallthrough)
ABSENT = {"__absent__": True}

#: sentinel distinguishing "resolve the newest snapshot yourself" from
#: an explicitly-passed None (= bucket has no snapshots)
_UNRESOLVED = object()


def is_absent_marker(row: Optional[dict]) -> bool:
    return bool(row) and row.get("__absent__") is True


def bucket_snapshots(store, volume: str, bucket: str) -> list[dict]:
    """This bucket's snapshot chain, oldest first."""
    out = [v for _, v in store.iterate(
        "open_keys", f"/.snapmeta/{volume}/{bucket}/")]
    out.sort(key=lambda v: v["created"])
    return out


def newest_snapshot(store, volume: str, bucket: str) -> Optional[dict]:
    """Single-pass newest-snapshot fetch for the mutation hot path (no
    sort; buckets without snapshots pay one empty indexed scan)."""
    newest = None
    for _, v in store.iterate("open_keys",
                              f"/.snapmeta/{volume}/{bucket}/"):
        if newest is None or v["created"] > newest["created"]:
            newest = v
    return newest


def preserve_preimage(store, volume: str, bucket: str,
                      full_key: str) -> None:
    """Copy-on-write first-write preservation (round 5; the reference
    gets snapshot isolation from O(1) RocksDB checkpoints — here the
    LIVE table stays authoritative and each COW snapshot accumulates
    only the PRE-IMAGES of rows mutated while it was newest). Call
    BEFORE mutating or deleting the live row `full_key`: if the
    bucket's newest snapshot is a COW snapshot that has no overlay
    entry for this key yet, the current live value (or an ABSENT
    marker) is recorded there. O(1) per mutation; snapshot creation is
    O(#snapshots) instead of O(bucket).

    Reads then resolve value-at-S as: the OLDEST overlay entry among
    snapshots >= S, else the live row — sound because a missing overlay
    entry in a snapshot's reign proves the key was not mutated during
    it. Pre-upgrade materialized snapshots read exactly as before: a
    COW snapshot is always newer than every materialized one in its
    chain, so the walk never crosses modes. Per-mutation cost: one scan
    of the bucket's snapmeta prefix (O(#snapshots), one empty indexed
    query for snapshot-less buckets) plus, when a COW snapshot is
    newest, a point read and at most one overlay write."""
    base = bucket_key(volume, bucket) + "/"
    _preserve_row(store, volume, bucket, "keys", full_key,
                  full_key[len(base):])


def preserve_fso_preimage(store, volume: str, bucket: str, table: str,
                          storage_key: str, newest=_UNRESOLVED) -> None:
    """COW preservation for FSO rows (dirs / files / dir_ids): the same
    first-write algebra as the OBS path, but the overlay key carries
    the TABLE and the id-keyed storage key (``#table#key``) — FSO paths
    are not stable under the O(1) directory reparent, so snapshot reads
    re-derive them by walking the directory tree AS OF the snapshot
    through ``snapshots.SnapshotStoreView``. Applies touching many rows
    resolve ``newest`` (newest_snapshot) once and pass it in, keeping
    one snapmeta scan per request."""
    _preserve_row(store, volume, bucket, table, storage_key,
                  f"#{table}#{storage_key}", newest=newest)


def _preserve_row(store, volume: str, bucket: str, table: str,
                  storage_key: str, rel: str, newest=_UNRESOLVED) -> None:
    if newest is _UNRESOLVED:
        newest = newest_snapshot(store, volume, bucket)
    if newest is None or not newest.get("cow"):
        return
    ok = f"{snap_prefix(volume, bucket, newest['snap_id'])}/{rel}"
    if store.get("keys", ok) is not None:
        return  # pre-image already captured for this snapshot
    old = store.get(table, storage_key)
    if old is not None:
        import json as _json

        # deep copy via the storage codec: the fetched dict aliases the
        # live cache row, which the calling apply mutates next
        old = _json.loads(_json.dumps(old))
    # journal=False like materialization: derived rows must not evict
    # the live-mutation history incremental snapdiff reads
    store.put("keys", ok, old if old is not None else dict(ABSENT),
              journal=False)


def is_snapmeta(open_key: str) -> bool:
    """True for snapshot-chain rows riding the open_keys table — every
    open-key scan must skip these or report snapshots as open files."""
    return open_key.startswith("/.snapmeta/")


@dataclass
class CreateSnapshot(OMRequest):
    """Bucket snapshot (OMSnapshotCreateRequest analog), chained to the
    previous snapshot; runs through the replicated log so HA replicas
    hold identical snapshot state.

    Every snapshot is COPY-ON-WRITE (round 5): apply writes only the
    chain metadata — O(#snapshots), the role the reference's O(1)
    RocksDB checkpoint plays — and the overlay fills lazily as
    ``preserve_preimage`` / ``preserve_fso_preimage`` capture the
    pre-image of each first mutation while this snapshot is newest.
    OBS/LEGACY overlays are path-keyed; FSO overlays are id-keyed
    (``#table#key`` over dirs/files/dir_ids, since paths go stale
    under the O(1) directory reparent) and FSO snapshot reads walk the
    directory tree as-of-snapshot through
    ``snapshots.SnapshotStoreView``."""

    volume: str
    bucket: str
    name: str
    snap_id: str = ""
    created: float = 0.0

    def pre_execute(self, om) -> None:
        import uuid

        self.snap_id = uuid.uuid4().hex[:12]
        self.created = time.time()

    def apply(self, store):
        if not self.name or "/" in self.name:
            # names ride the .snapshot/<name>/<key> path convention and
            # the snapmeta key space: a slash or empty name would make
            # the snapshot unaddressable
            raise OMError("INVALID_SNAPSHOT_NAME", repr(self.name))
        brow = store.get("buckets", bucket_key(self.volume, self.bucket))
        if brow is None:
            raise OMError(BUCKET_NOT_FOUND, f"{self.volume}/{self.bucket}")
        meta_key = snapmeta_key(self.volume, self.bucket, self.name)
        if store.exists("open_keys", meta_key):
            raise OMError("SNAPSHOT_EXISTS", self.name)
        # chain head: the newest existing snapshot of this bucket
        prev, prev_created = None, -1.0
        for _, v in store.iterate(
            "open_keys", f"/.snapmeta/{self.volume}/{self.bucket}/"
        ):
            if v["created"] > prev_created:
                prev, prev_created = v["snap_id"], v["created"]
        info = {
            "volume": self.volume,
            "bucket": self.bucket,
            "name": self.name,
            "snap_id": self.snap_id,
            "created": self.created,
            "previous": prev,
        }
        info["cow"] = True
        if brow.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            # FSO overlays are id-keyed (#table#key) and reads walk the
            # directory tree as-of-snapshot via SnapshotStoreView
            info["fso"] = True
        store.put("open_keys", meta_key, info)
        # local journal position of this snapshot: lets snapdiff walk
        # only the updates BETWEEN two snapshots instead of listing the
        # whole namespace (the compaction-DAG role of the reference's
        # RocksDBCheckpointDiffer)
        store.snapshot_markers[self.snap_id] = store.txid
        return info


@dataclass
class DeleteSnapshot(OMRequest):
    """Drop a snapshot's rows and chain entry. A COW snapshot first
    merges its overlay DOWN into the adjacent OLDER snapshot (the
    reference's snapshot-deletion deep-clean moves deleted-key state
    the same direction): an entry preserved here may be the truth for
    reads at older snapshots whose reigns saw no mutation of that key.
    O(overlay) — proportional to changes, never the namespace. Entries
    never merge into a MATERIALIZED older snapshot: its row set is
    already complete for its moment."""

    volume: str
    bucket: str
    name: str

    def apply(self, store):
        meta_key = snapmeta_key(self.volume, self.bucket, self.name)
        info = store.get("open_keys", meta_key)
        if info is None:
            raise OMError("SNAPSHOT_NOT_FOUND", self.name)
        prefix = snap_prefix(self.volume, self.bucket, info["snap_id"])
        if info.get("cow"):
            snaps = bucket_snapshots(store, self.volume, self.bucket)
            idx = next(i for i, s in enumerate(snaps)
                       if s["snap_id"] == info["snap_id"])
            older = snaps[idx - 1] if idx > 0 else None
            if older is not None and older.get("cow"):
                op = snap_prefix(self.volume, self.bucket,
                                 older["snap_id"])
                for k, v in list(store.iterate("keys", prefix + "/")):
                    rel = k[len(prefix) + 1:]
                    if store.get("keys", f"{op}/{rel}") is None:
                        store.put("keys", f"{op}/{rel}", v,
                                  journal=False)
        for k, _ in list(store.iterate("keys", prefix)):
            store.delete("keys", k, journal=False)
        store.delete("open_keys", meta_key)
        store.snapshot_markers.pop(info["snap_id"], None)
        return info


@dataclass
class RenameSnapshot(OMRequest):
    """Rename a snapshot's chain entry (OMSnapshotRenameRequest /
    WebHDFS RENAMESNAPSHOT analog). The materialized rows are keyed by
    snap_id and the journal marker by the same id, so only the
    name-keyed metadata row moves — O(1)."""

    volume: str
    bucket: str
    name: str
    new_name: str

    def apply(self, store):
        if not self.new_name or "/" in self.new_name:
            raise OMError("INVALID_SNAPSHOT_NAME", repr(self.new_name))
        mk = snapmeta_key(self.volume, self.bucket, self.name)
        info = store.get("open_keys", mk)
        if info is None:
            raise OMError("SNAPSHOT_NOT_FOUND", self.name)
        nk = snapmeta_key(self.volume, self.bucket, self.new_name)
        if store.exists("open_keys", nk):
            raise OMError("SNAPSHOT_EXISTS", self.new_name)
        info["name"] = self.new_name
        store.delete("open_keys", mk)
        store.put("open_keys", nk, info)
        return info


@dataclass
class SetQuota(OMRequest):
    """Set space/namespace quota on a volume (bucket="") or bucket
    (ozone sh volume/bucket setquota analog). None leaves a dimension
    unchanged; -1 clears it to unlimited — setting one quota never
    silently wipes the other."""

    volume: str
    bucket: str = ""
    quota_bytes: Optional[int] = None
    quota_namespace: Optional[int] = None

    def apply(self, store):
        if self.bucket:
            k, table = bucket_key(self.volume, self.bucket), "buckets"
            missing = BUCKET_NOT_FOUND
        else:
            k, table = volume_key(self.volume), "volumes"
            missing = VOLUME_NOT_FOUND
        row = store.get(table, k)
        if row is None:
            raise OMError(missing, k)
        if self.quota_bytes is not None:
            row["quota_bytes"] = int(self.quota_bytes)
        if self.quota_namespace is not None:
            row["quota_namespace"] = int(self.quota_namespace)
        store.put(table, k, row)
        return row


@dataclass
class SetVolumeOwner(OMRequest):
    """Transfer volume ownership (ozone sh volume update --user,
    OMVolumeSetOwnerRequest)."""

    volume: str
    owner: str

    def pre_execute(self, om) -> None:
        if not self.owner:
            raise OMError(INVALID_REQUEST, "new owner must be non-empty")

    def apply(self, store):
        k = volume_key(self.volume)
        row = store.get("volumes", k)
        if row is None:
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        row["owner"] = self.owner
        store.put("volumes", k, row)
        return row


@dataclass
class ApplyQuotaRepair(OMRequest):
    """Apply PRE-COMPUTED per-bucket usage deltas (the OM quota-repair
    service's replicated half). The O(all keys) recount runs OUTSIDE
    the apply lock as a paged background scan
    (``OzoneManager.repair_quota``, QuotaRepairTask analog); this apply
    touches one row per bucket plus the volume row, so a repair of a
    billion-key namespace never stalls the ring's writers. Deltas (not
    absolutes) keep live traffic honest: a key committed after its page
    was scanned keeps its own increment — the delta fixes only the
    pre-existing drift the scan measured."""

    volume: str
    #: bucket_key -> [d_used_bytes, d_key_count]
    deltas: dict = None  # type: ignore[assignment]

    def apply(self, store):
        vk = volume_key(self.volume)
        vrow = store.get("volumes", vk)
        if vrow is None:
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        out = {}
        for bk, (d_used, d_keys) in (self.deltas or {}).items():
            brow = store.get("buckets", bk)
            if brow is None:
                continue  # bucket deleted between scan and apply
            brow["used_bytes"] = int(brow.get("used_bytes", 0)) + int(d_used)
            brow["key_count"] = int(brow.get("key_count", 0)) + int(d_keys)
            store.put("buckets", bk, brow)
            out[bk] = {"used_bytes": brow["used_bytes"],
                       "key_count": brow["key_count"]}
        # volume totals re-derive from the adjusted bucket rows:
        # O(#buckets), never O(keys)
        vtotal = vkeys = 0
        for _, brow in store.iterate("buckets", f"/{self.volume}/"):
            vtotal += int(brow.get("used_bytes", 0))
            vkeys += int(brow.get("key_count", 0))
        vrow["used_bytes"] = vtotal
        vrow["key_count"] = vkeys
        store.put("volumes", vk, vrow)
        return {"volume_used_bytes": vtotal, "volume_key_count": vkeys,
                "buckets": out}


@dataclass
class RecoverLease(OMRequest):
    """Finalize an abandoned hsynced write (OMRecoverLeaseRequest analog +
    the ozonefs adapter's recoverLease): the key is sealed at its last
    hsynced length, every open session for it is dropped, and the dead
    writer is fenced — its eventual commit fails on the missing session.
    Works on both OBS keys and FSO files (path resolved against the
    bucket layout)."""

    volume: str
    bucket: str
    key: str
    modified: float = 0.0

    def pre_execute(self, om) -> None:
        self.modified = time.time()

    def apply(self, store):
        from ozone_tpu.om import fso

        b = store.get("buckets", bucket_key(self.volume, self.bucket))
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, f"{self.volume}/{self.bucket}")
        if b.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            parent_id, name = fso.resolve_parent(
                store, self.volume, self.bucket, self.key
            )
            ek = fso.dir_key(self.volume, self.bucket, parent_id, name)
            table = "files"
        else:
            ek = key_key(self.volume, self.bucket, self.key)
            table = "keys"
        cur = store.get(table, ek)
        sessions = direct_sessions_of(store, ek)
        for s in sessions:
            store.delete("open_keys", s)
        if cur is not None:
            if table == "keys":
                preserve_preimage(store, self.volume, self.bucket, ek)
            else:
                preserve_fso_preimage(store, self.volume, self.bucket,
                                      "files", ek)
            if cur.pop("hsync_client_id", None) is not None:
                cur["modified"] = self.modified
                store.put(table, ek, cur)
            return {"recovered": True, "key": cur}
        if sessions:
            # never hsynced: nothing visible to seal; dropping the
            # sessions abandons the uncommitted chunks (unreferenced on
            # the datanodes, reclaimed by scrubbing)
            return {"recovered": False, "key": None}
        raise OMError(KEY_NOT_FOUND,
                      f"{self.volume}/{self.bucket}/{self.key}")


FILE_ALREADY_EXISTS = "FILE_ALREADY_EXISTS"
NOT_A_DIRECTORY = "NOT_A_DIRECTORY"


def check_fs_conflicts(store, volume: str, bucket: str,
                       key: str) -> None:
    """LEGACY filesystem-shape invariants on the flat key table (the
    reference's checkDirectoryAlreadyExists / checkKeyExists pair): a
    file and a directory marker may not share a name in either
    direction, and no ancestor of a new entry may be a plain file."""
    base = key.rstrip("/")
    if not key.endswith("/") and store.exists(
            "keys", key_key(volume, bucket, base + "/")):
        raise OMError(FILE_ALREADY_EXISTS,
                      f"{base} exists as a directory")
    if key.endswith("/") and store.exists(
            "keys", key_key(volume, bucket, base)):
        raise OMError(FILE_ALREADY_EXISTS, f"{base} exists as a file")
    parts = base.split("/")[:-1]
    for i in range(1, len(parts) + 1):
        anc = "/".join(parts[:i])
        if store.exists("keys", key_key(volume, bucket, anc)):
            raise OMError(NOT_A_DIRECTORY, f"ancestor {anc} is a file")


def missing_parent_markers(store, volume: str, bucket: str,
                           key: str) -> list[str]:
    parts = key.rstrip("/").split("/")[:-1]
    out = []
    for i in range(1, len(parts) + 1):
        marker = "/".join(parts[:i]) + "/"
        if not store.exists("keys",
                            key_key(volume, bucket, marker)):
            out.append(marker)
    return out


def put_parent_markers(store, volume: str, bucket: str,
                       markers: list[str], replication: str,
                       ts: float) -> None:
    """Materialize LEGACY parent directory markers. Callers charge the
    namespace quota for them FIRST (one count per marker) so live
    enforcement, delete accounting (DeleteKey charges -1 per marker),
    and RepairQuota's recount all agree."""
    for marker in markers:
        preserve_preimage(store, volume, bucket,
                          key_key(volume, bucket, marker))
        store.put("keys", key_key(volume, bucket, marker), {
            "volume": volume,
            "bucket": bucket,
            "name": marker,
            "replication": replication,
            "size": 0,
            "block_groups": [],
            "created": ts,
            "modified": ts,
        })


def normalize_fs_path(key: str) -> str:
    """LEGACY-bucket filesystem-path normalization (the reference's
    `ozone.om.enable.filesystem.paths` posture, OmUtils.normalizeKey):
    collapse duplicate separators, strip a leading '/', refuse '.'/'..'
    segments. A trailing '/' (directory marker) survives."""
    is_dir = key.endswith("/")
    parts = [p for p in key.split("/") if p]
    if not parts:
        raise OMError(INVALID_REQUEST, f"empty key {key!r}")
    for p in parts:
        if p in (".", ".."):
            raise OMError(INVALID_REQUEST,
                          f"illegal path segment {p!r} in {key!r}")
    return "/".join(parts) + ("/" if is_dir else "")


@dataclass
class OpenKey(OMRequest):
    """Record an open-key session (OMKeyCreateRequest analog — block
    allocation happens in pre_execute via SCM, like the reference's
    preExecute asking SCM for blocks). `fs_paths` marks a LEGACY-layout
    bucket: the flat key table gains filesystem semantics — ancestor
    file/directory conflicts are refused here, and the commit
    materializes the missing parent directory markers (the reference's
    BucketLayoutAwareOMKeyRequestFactory routes LEGACY through the same
    flat-table requests with these extra checks)."""

    volume: str
    bucket: str
    key: str
    client_id: str
    replication: str
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    created: float = 0.0
    metadata: dict = field(default_factory=dict)
    fs_paths: bool = False
    #: envelope-encryption bundle minted by the OM at open (EDEK for a
    #: TDE bucket, plaintext per-key secret for a GDPR bucket); rides
    #: the replicated request so every replica stores the same bundle
    encryption: dict = field(default_factory=dict)
    #: stable identity of THIS key version (OmKeyInfo objectID): renames
    #: carry it unchanged, overwrites mint a fresh one — snapdiff pairs
    #: deleted+added rows by it to report RENAME entries
    key_id: str = ""
    #: explicit key ACLs fixed at open (OmKeyArgs acls — a rewrite
    #: carries the source key's grants so the commit can't re-inherit
    #: broader bucket defaults); empty = inherit defaults at commit
    acls: list = field(default_factory=list)

    def pre_execute(self, om) -> None:
        import uuid

        self.created = time.time()
        self.key_id = uuid.uuid4().hex[:16]

    def apply(self, store):
        if not store.exists("buckets", bucket_key(self.volume, self.bucket)):
            raise OMError(BUCKET_NOT_FOUND, f"{self.volume}/{self.bucket}")
        if self.fs_paths:
            check_fs_conflicts(store, self.volume, self.bucket,
                               self.key)
        kk = key_key(self.volume, self.bucket, self.key)
        row = {
            "volume": self.volume,
            "bucket": self.bucket,
            "name": self.key,
            "object_id": self.key_id,
            "replication": self.replication,
            "checksum_type": self.checksum_type,
            "bytes_per_checksum": self.bytes_per_checksum,
            "size": 0,
            "block_groups": [],
            "created": self.created,
            "modified": self.created,
        }
        if self.metadata:
            # user-defined key metadata (reference: OmKeyInfo metadata
            # map carrying e.g. S3 x-amz-meta-* pairs)
            row["metadata"] = dict(self.metadata)
        if self.acls:
            row["acls"] = list(self.acls)
        if self.fs_paths:
            row["fs_paths"] = True  # commit materializes parent markers
        if self.encryption:
            row["encryption"] = dict(self.encryption)
        store.put("open_keys", f"{kk}/{self.client_id}", row)


def erase_gdpr_secret(info: dict) -> None:
    """GDPR right-to-erasure: destroy the per-key encryption secret in
    the SAME apply that deletes the key. The blocks ride the async
    purge chain, but without the secret they are ciphertext noise from
    this moment on (the reference's GDPR_FLAG crypto-erasure)."""
    enc = info.get("encryption")
    if enc and "gdpr_secret" in enc:
        info["encryption"] = {"erased": True}


@dataclass
class DeleteKey(OMRequest):
    """Move a key to the deleted table for async purge (OMKeyDeleteRequest +
    KeyDeletingService pattern). `expect_object_id` ("" = unfenced) makes
    the delete conditional on the live row still being the scanned
    version — the lifecycle sweeper's TTL expiration uses it so a user
    overwrite racing the sweep always wins (same contract as the
    transition path's rewrite fence)."""

    volume: str
    bucket: str
    key: str
    ts: float = 0.0
    expect_object_id: str = ""

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        kk = key_key(self.volume, self.bucket, self.key)
        info = store.get("keys", kk)
        if info is None:
            raise OMError(KEY_NOT_FOUND, kk)
        if self.expect_object_id and \
                info.get("object_id") != self.expect_object_id:
            raise OMError(KEY_MODIFIED,
                          f"{kk} overwritten since the expiry scan")
        preserve_preimage(store, self.volume, self.bucket, kk)
        store.delete("keys", kk)
        # deleting a live hsync stream: fence its writer before the blocks
        # hit the purge chain, or its commit would resurrect purged blocks
        stale_writer = info.get("hsync_client_id")
        if stale_writer:
            store.delete("open_keys", f"{kk}/{stale_writer}")
        erase_gdpr_secret(info)
        store.put("deleted_keys", f"{kk}:{self.ts}", info)
        check_and_charge_quota(store, self.volume, self.bucket,
                               -int(info.get("size", 0)), -1)
        return info


def check_attr_preconds(info: dict, preconds: dict) -> None:
    """XAttr flag semantics, enforced INSIDE the serialized apply
    (WebHDFS SETXATTR CREATE/REPLACE, REMOVEXATTR existence): value
    True = the attr must exist, False = it must not. A gateway-side
    read-then-write check would race concurrent setters."""
    have = info.get("attrs", {})
    for name, must_exist in (preconds or {}).items():
        if must_exist and name not in have:
            raise OMError("XATTR_NOT_FOUND", name)
        if not must_exist and name in have:
            raise OMError("XATTR_EXISTS", name)


@dataclass
class SetKeyAttrs(OMRequest):
    """Merge filesystem attributes (owner/group/permission/mtime/atime)
    into a key or directory-marker row (reference: HttpFS SETOWNER /
    SETPERMISSION / SETTIMES land in KeyManagerImpl setattr paths; OBS
    layout stores them on the key info). A None value deletes the
    attribute. `preconds` maps attr name -> must-exist bool, checked
    atomically here (xattr CREATE/REPLACE flags)."""

    volume: str
    bucket: str
    key: str
    attrs: dict
    preconds: dict = field(default_factory=dict)

    def apply(self, store):
        kk = key_key(self.volume, self.bucket, self.key)
        info = store.get("keys", kk)
        if info is None:  # directory marker
            kk = key_key(self.volume, self.bucket, self.key + "/")
            info = store.get("keys", kk)
        if info is None:
            raise OMError(KEY_NOT_FOUND, kk)
        preserve_preimage(store, self.volume, self.bucket, kk)
        check_attr_preconds(info, self.preconds)
        merged = dict(info.get("attrs", {}))
        for k, v in self.attrs.items():
            if v is None:
                merged.pop(k, None)
            else:
                merged[k] = v
        info["attrs"] = merged
        store.put("keys", kk, info)
        return info


@dataclass
class RenameKey(OMRequest):
    volume: str
    bucket: str
    key: str
    new_key: str
    #: LEGACY layout: the destination obeys filesystem shape (conflict
    #: checks + parent markers), same as OpenKey/CommitKey
    fs_paths: bool = False
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        src = key_key(self.volume, self.bucket, self.key)
        info = store.get("keys", src)
        if info is None:
            raise OMError(KEY_NOT_FOUND, src)
        dst = key_key(self.volume, self.bucket, self.new_key)
        if self.fs_paths:
            check_fs_conflicts(store, self.volume, self.bucket,
                               self.new_key)
            markers = missing_parent_markers(store, self.volume,
                                             self.bucket, self.new_key)
            if markers:
                check_and_charge_quota(store, self.volume, self.bucket,
                                       0, len(markers))
                put_parent_markers(store, self.volume, self.bucket,
                                   markers,
                                   info.get("replication", ""),
                                   self.ts or time.time())
        # both ends change: the source row disappears and the
        # destination row is created/overwritten
        preserve_preimage(store, self.volume, self.bucket, src)
        preserve_preimage(store, self.volume, self.bucket, dst)
        info["name"] = self.new_key
        store.delete("keys", src)
        store.put("keys", dst, info)


@dataclass
class SetS3Secret(OMRequest):
    """Store an access-id's S3 secret (reference: S3GetSecretRequest
    creates on first fetch; OMSetSecretRequest overwrites). With
    if_absent, the get-or-create is atomic inside apply so concurrent
    first fetches converge on one secret."""

    access_id: str
    secret: str
    if_absent: bool = False

    def apply(self, store):
        if self.if_absent:
            row = store.get("s3_secrets", self.access_id)
            if row is not None:
                return row["secret"]
        store.put(
            "s3_secrets", self.access_id,
            {"access_id": self.access_id, "secret": self.secret},
        )
        return self.secret


@dataclass
class RevokeS3Secret(OMRequest):
    access_id: str

    def apply(self, store):
        store.delete("s3_secrets", self.access_id)


@dataclass
class SetBucketAcl(OMRequest):
    """Replace a bucket's ACL grant list (reference: OMBucketSetAclRequest;
    S3 grants map onto the bucket record)."""

    volume: str
    bucket: str
    acl: list[dict] = field(default_factory=list)

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        b["acl"] = self.acl
        store.put("buckets", k, b)


@dataclass
class CreateMasterKey(OMRequest):
    """Mint (or rotate) a named KMS master key. The key material is
    generated in pre_execute on the leader and replicates through the
    log — every OM replica can unwrap EDEKs (the reference delegates
    this to an external Hadoop KMS; here the metadata ring IS the key
    authority)."""

    name: str
    rotate: bool = False
    material: str = ""

    def pre_execute(self, om) -> None:
        import os as _os

        self.material = _os.urandom(32).hex()

    def apply(self, store):
        from ozone_tpu.utils.kms import MASTER_PREFIX

        k = MASTER_PREFIX + self.name
        row = store.get("system", k)
        if row is None:
            if self.rotate:
                raise OMError(INVALID_REQUEST,
                              f"no master key {self.name!r} to rotate")
            row = {"versions": []}
        elif not self.rotate:
            raise OMError(INVALID_REQUEST,
                          f"master key {self.name!r} exists")
        row["versions"].append(self.material)
        store.put("system", k, row)
        return {"name": self.name, "versions": len(row["versions"])}


@dataclass
class SetBucketAttrs(OMRequest):
    """Merge filesystem attributes onto the bucket row itself — the
    ofs model exposes /volume/bucket as a directory, so chmod/chown on
    a mount's top level must land somewhere (HttpFS SETPERMISSION on a
    bucket-root path). None values delete."""

    volume: str
    bucket: str
    attrs: dict = field(default_factory=dict)

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        merged = dict(b.get("attrs", {}))
        for key, v in self.attrs.items():
            if v is None:
                merged.pop(key, None)
            else:
                merged[key] = v
        b["attrs"] = merged
        store.put("buckets", k, b)
        return b


@dataclass
class SetBucketReplication(OMRequest):
    """Change a bucket's default replication config (ozone sh bucket
    set-replication-config, shell/bucket/SetReplicationConfigHandler +
    OMBucketSetPropertyRequest): applies to keys written AFTER the
    change — existing keys keep their config until rewritten (`key
    rewrite`)."""

    volume: str
    bucket: str
    replication: str

    def pre_execute(self, om) -> None:
        from ozone_tpu.scm.pipeline import ReplicationConfig

        ReplicationConfig.parse(self.replication)  # raises on nonsense

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        b["replication"] = self.replication
        store.put("buckets", k, b)
        return b


PREFIX_NOT_FOUND = "PREFIX_NOT_FOUND"
TENANT_ALREADY_EXISTS = "TENANT_ALREADY_EXISTS"
TENANT_NOT_FOUND = "TENANT_NOT_FOUND"
TENANT_NOT_EMPTY = "TENANT_NOT_EMPTY"
ACCESS_ID_NOT_FOUND = "ACCESS_ID_NOT_FOUND"
ACCESS_ID_ALREADY_EXISTS = "ACCESS_ID_ALREADY_EXISTS"
INVALID_REQUEST = "INVALID_REQUEST"
PERMISSION_DENIED = "PERMISSION_DENIED"

_OBJ_TABLES = {"volume": "volumes", "bucket": "buckets", "key": "keys"}


def _acl_target(store, obj_type: str, volume: str, bucket: str, path: str):
    """(table, row_key) for an ACL object; prefix rows are created on
    demand (the reference's prefixTable upserts). Keys resolve through
    the flat table for OBS buckets and the parent-id-keyed file table for
    FSO buckets (reference: BucketLayoutAwareOMKeyRequestFactory)."""
    from ozone_tpu.om import acl as aclmod

    if obj_type == "volume":
        return "volumes", volume_key(volume)
    if obj_type == "bucket":
        return "buckets", bucket_key(volume, bucket)
    if obj_type == "key":
        flat = f"/{volume}/{bucket}/{path}"
        if store.exists("keys", flat):
            return "keys", flat
        b = store.get("buckets", bucket_key(volume, bucket))
        if b is not None and b.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            from ozone_tpu.om import fso

            try:
                parent_id, name = fso.resolve_parent(store, volume, bucket,
                                                     path)
            except OMError:
                return "keys", flat  # unreachable path -> KEY_NOT_FOUND
            fk = fso.dir_key(volume, bucket, parent_id, name)
            if store.exists("files", fk):
                return "files", fk
        return "keys", flat
    if obj_type == "prefix":
        return "prefixes", aclmod.prefix_key(volume, bucket, path)
    raise OMError(INVALID_REQUEST, f"unknown acl object type {obj_type}")


@dataclass
class ModifyAcl(OMRequest):
    """Add/remove/replace native ACL grants on volume/bucket/key/prefix
    (reference: OM*AddAclRequest / *RemoveAclRequest / *SetAclRequest
    families + OMPrefixAclRequest)."""

    obj_type: str  # volume | bucket | key | prefix
    volume: str
    bucket: str = ""
    path: str = ""
    op: str = "add"  # add | remove | set
    acls: list[dict] = field(default_factory=list)

    def apply(self, store):
        from ozone_tpu.om import acl as aclmod

        if self.op not in ("add", "remove", "set"):
            raise OMError(INVALID_REQUEST, f"unknown acl op {self.op!r}")
        table, k = _acl_target(store, self.obj_type, self.volume,
                               self.bucket, self.path)
        row = store.get(table, k)
        if row is None:
            if table == "prefixes":
                row = {"acls": []}
            else:
                raise OMError(
                    {"volumes": VOLUME_NOT_FOUND,
                     "buckets": BUCKET_NOT_FOUND,
                     "keys": KEY_NOT_FOUND,
                     "files": KEY_NOT_FOUND}[table], k)
        elif table == "keys":
            preserve_preimage(store, self.volume, self.bucket, k)
        elif table == "files":
            preserve_fso_preimage(store, self.volume, self.bucket,
                                  "files", k)
        existing = row.get("acls", [])
        changed = False
        if self.op == "set":
            row["acls"] = list(self.acls)
            changed = True
        else:
            fn = aclmod.add_acl if self.op == "add" else aclmod.remove_acl
            for d in self.acls:
                existing, ch = fn(existing, aclmod.OzoneAcl.from_json(d))
                changed = changed or ch
            row["acls"] = existing
        if changed:
            store.put(table, k, row)
        return changed


@dataclass
class CreateTenant(OMRequest):
    """Create a tenant backed by its own volume (reference:
    OMTenantCreateRequest — tenant name == volume unless overridden)."""

    tenant: str
    volume: str = ""
    owner: str = "root"
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()
        if not self.volume:
            self.volume = self.tenant

    def apply(self, store):
        if store.exists("tenants", self.tenant):
            raise OMError(TENANT_ALREADY_EXISTS, self.tenant)
        vk = volume_key(self.volume)
        # the tenant volume must be fresh: adopting an existing volume
        # (s3v, another owner's namespace) would hand the tenant's users
        # its entire contents (reference OMTenantCreateRequest fails the
        # same way)
        if store.exists("volumes", vk):
            raise OMError(VOLUME_ALREADY_EXISTS,
                          f"tenant volume {self.volume} already exists")
        store.put("volumes", vk, {
            "name": self.volume,
            "owner": self.owner,
            "quota_bytes": -1,
            "created": self.created,
        })
        store.put("tenants", self.tenant, {
            "tenant": self.tenant,
            "volume": self.volume,
            "created": self.created,
        })


@dataclass
class DeleteTenant(OMRequest):
    tenant: str

    def apply(self, store):
        if not store.exists("tenants", self.tenant):
            raise OMError(TENANT_NOT_FOUND, self.tenant)
        for _, row in store.iterate("tenant_access"):
            if row["tenant"] == self.tenant:
                raise OMError(TENANT_NOT_EMPTY,
                              f"{self.tenant} still has access ids")
        store.delete("tenants", self.tenant)


@dataclass
class AssignUserToTenant(OMRequest):
    """Grant a user an S3 access id under a tenant (reference:
    OMTenantAssignUserAccessIdRequest: accessId = tenant$user, S3 secret
    minted and stored)."""

    tenant: str
    user: str
    access_id: str = ""
    secret: str = ""

    def pre_execute(self, om) -> None:
        import secrets as _secrets

        if not self.access_id:
            self.access_id = f"{self.tenant}${self.user}"
        if not self.secret:
            self.secret = _secrets.token_hex(20)

    def apply(self, store):
        if not store.exists("tenants", self.tenant):
            raise OMError(TENANT_NOT_FOUND, self.tenant)
        # never adopt or rotate an existing identity: that would silently
        # invalidate issued credentials or re-point another tenant's
        # access id here (reference: TENANT_ACCESS_ID_ALREADY_EXISTS)
        if store.exists("tenant_access", self.access_id) or \
                store.exists("s3_secrets", self.access_id):
            raise OMError(ACCESS_ID_ALREADY_EXISTS, self.access_id)
        store.put("tenant_access", self.access_id, {
            "access_id": self.access_id,
            "tenant": self.tenant,
            "user": self.user,
        })
        store.put("s3_secrets", self.access_id, {
            "access_id": self.access_id,
            "secret": self.secret,
        })
        return {"access_id": self.access_id, "secret": self.secret}


@dataclass
class RevokeUserAccessId(OMRequest):
    access_id: str

    def apply(self, store):
        if not store.exists("tenant_access", self.access_id):
            raise OMError(ACCESS_ID_NOT_FOUND, self.access_id)
        store.delete("tenant_access", self.access_id)
        store.delete("s3_secrets", self.access_id)


LIFECYCLE_FENCED = "LIFECYCLE_FENCED"
NO_SUCH_LIFECYCLE = "NO_SUCH_LIFECYCLE"


@dataclass
class SetBucketLifecycle(OMRequest):
    """Install a bucket's lifecycle rules (the S3
    PutBucketLifecycleConfiguration analog; Apache Ozone 1.5 has no
    bucket lifecycle — this is the tiering extension's policy store).
    Rules ride the bucket row, so they replicate through the metadata
    ring and survive failover like every other bucket property."""

    volume: str
    bucket: str
    rules: list = field(default_factory=list)

    def pre_execute(self, om) -> None:
        from ozone_tpu.lifecycle.policy import (
            LifecycleError,
            validate_rules,
        )

        try:
            self.rules = validate_rules(self.rules)
        except LifecycleError as e:
            raise OMError(INVALID_REQUEST, str(e))

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        if b.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            # the sweeper evaluates prefix rules over the flat key scan;
            # FSO namespaces are id-keyed, so accepting rules here would
            # configure a silent no-op (deterministic rejection instead)
            raise OMError(
                INVALID_REQUEST,
                "lifecycle rules are not supported on "
                "FILE_SYSTEM_OPTIMIZED buckets (docs/OPERATIONS.md)")
        b["lifecycle"] = list(self.rules)
        store.put("buckets", k, b)
        return b


@dataclass
class DeleteBucketLifecycle(OMRequest):
    volume: str
    bucket: str

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        b.pop("lifecycle", None)
        store.put("buckets", k, b)
        return b


@dataclass
class LifecycleCheckpoint(OMRequest):
    """Lifecycle sweeper state: fencing term + resumable scan cursor,
    committed through the ring so a restarted or failed-over sweeper
    resumes exactly where the last durable checkpoint left off.

    Term fencing (the scm/sequence_id.py commit-first treatment applied
    to a background service): a `fence` checkpoint claims the sweeper
    role for `term` and is rejected if a HIGHER term already claimed
    it; a plain checkpoint is rejected unless its term IS the fenced
    term. Every replica applies the same deterministic rejection, so a
    deposed lifecycle leader's late cursor commits can never regress or
    double-apply the scan — kill -9 of the leader mid-sweep loses at
    most one un-checkpointed page, which re-scans idempotently."""

    term: int
    cursor: dict = field(default_factory=dict)
    stats: dict = field(default_factory=dict)
    fence: bool = False

    def apply(self, store):
        row = store.get("system", "lifecycle_state") or {"term": -1}
        fenced = int(row.get("term", -1))
        if self.fence:
            if int(self.term) < fenced:
                raise OMError(
                    LIFECYCLE_FENCED,
                    f"fence term {self.term} < current {fenced}")
            row["term"] = int(self.term)
        else:
            if int(self.term) != fenced:
                raise OMError(
                    LIFECYCLE_FENCED,
                    f"checkpoint term {self.term} != fenced {fenced}")
            row["cursor"] = dict(self.cursor)
            if self.stats:
                row["stats"] = dict(self.stats)
        store.put("system", "lifecycle_state", row)
        return dict(row)


# ------------------------------------------------- geo replication (DR)

GEO_FENCED = "GEO_FENCED"


@dataclass
class SetBucketGeoReplication(OMRequest):
    """Install a bucket's cross-cluster replication rules (the S3
    PutBucketReplication analog; Apache Ozone 1.5 has no bucket-level
    geo replication — PARITY row 47). Rules ride the bucket row, so
    they replicate through the metadata ring and survive failover like
    every other bucket property; the ReplicationShipper
    (replication_geo/shipper.py) enforces them."""

    volume: str
    bucket: str
    rules: list = field(default_factory=list)

    def pre_execute(self, om) -> None:
        from ozone_tpu.replication_geo.rules import (
            GeoReplicationError,
            validate_rules,
        )

        try:
            self.rules = validate_rules(self.rules)
        except GeoReplicationError as e:
            raise OMError(INVALID_REQUEST, str(e))

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        if b.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            # the shipper tails the flat `keys` table; FSO namespaces
            # commit through the `files` table, so accepting rules here
            # would configure a silent no-op (deterministic rejection
            # instead, same contract as lifecycle)
            raise OMError(
                INVALID_REQUEST,
                "geo replication rules are not supported on "
                "FILE_SYSTEM_OPTIMIZED buckets (docs/OPERATIONS.md)")
        b["geo_replication"] = list(self.rules)
        store.put("buckets", k, b)
        return b


@dataclass
class DeleteBucketGeoReplication(OMRequest):
    volume: str
    bucket: str

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        b.pop("geo_replication", None)
        store.put("buckets", k, b)
        return b


@dataclass
class GeoCheckpoint(OMRequest):
    """Replication shipper state: fencing term + the WAL-delta cursor
    (last shipped journal txid) + the set of buckets whose initial
    reconcile completed, committed through the ring so a restarted or
    failed-over shipper resumes exactly at the last durable page.

    Term fencing is the LifecycleCheckpoint treatment verbatim: a
    `fence` checkpoint claims the shipper role for `term` and is
    rejected if a HIGHER term already claimed it; a plain checkpoint is
    rejected unless its term IS the fenced term. Every replica applies
    the same deterministic rejection, so a deposed shipper's late
    cursor commits can never regress the WAL position — kill -9 of the
    shipper leader mid-page loses at most one un-checkpointed page,
    which re-ships idempotently (the destination's geo-src-oid marker
    makes the re-apply a no-op)."""

    term: int
    cursor: dict = field(default_factory=dict)
    stats: dict = field(default_factory=dict)
    #: None = leave the bootstrapped-bucket set unchanged
    bootstrapped: Optional[list] = None
    fence: bool = False

    def apply(self, store):
        row = store.get("system", "geo_state") or {"term": -1}
        fenced = int(row.get("term", -1))
        if self.fence:
            if int(self.term) < fenced:
                raise OMError(
                    GEO_FENCED,
                    f"fence term {self.term} < current {fenced}")
            row["term"] = int(self.term)
        else:
            if int(self.term) != fenced:
                raise OMError(
                    GEO_FENCED,
                    f"checkpoint term {self.term} != fenced {fenced}")
            row["cursor"] = dict(self.cursor)
            if self.bootstrapped is not None:
                row["bootstrapped"] = list(self.bootstrapped)
            if self.stats:
                row["stats"] = dict(self.stats)
        store.put("system", "geo_state", row)
        return dict(row)


@dataclass
class PurgeDeletedKeys(OMRequest):
    """Remove processed entries from the deleted table (background
    KeyDeletingService completion)."""

    entries: list[str] = field(default_factory=list)

    def apply(self, store):
        for k in self.entries:
            store.delete("deleted_keys", k)


# ------------------------------------------------------- delegation tokens

TOKEN_ERROR = "TOKEN_ERROR"


@dataclass
class NewDTokenMasterKey(OMRequest):
    """Install a delegation-token master key (reference: the
    OzoneDelegationTokenSecretManager rolling its master key and
    persisting it through OMUpdateDelegationTokenRequest so every HA
    replica signs/verifies identically). The leader mints material in
    pre_execute; apply installs it verbatim — deterministic on replicas."""

    key_id: str = ""
    material: str = ""
    created: float = 0.0
    expires: float = 0.0
    if_absent: bool = True

    def pre_execute(self, om) -> None:
        import secrets as _secrets

        self.key_id = _secrets.token_hex(8)
        self.material = _secrets.token_bytes(32).hex()
        self.created = time.time()
        self.expires = self.created + om.dtoken_key_lifetime_s

    def apply(self, store):
        from ozone_tpu.om import dtokens

        if self.if_absent:
            cur = dtokens.current_key(store, now=self.created)
            if cur is not None:
                return cur["key_id"]
        store.put("dtoken_keys", self.key_id, {
            "key_id": self.key_id,
            "material": self.material,
            "created": self.created,
            "expires": self.expires,
        })
        return self.key_id


@dataclass
class StoreDelegationToken(OMRequest):
    """Persist an issued token's server-side row (the dTokenTable write
    in OMGetDelegationTokenRequest.validateAndUpdateCache)."""

    ident: dict = field(default_factory=dict)
    expiry: float = 0.0

    def apply(self, store):
        row = dict(self.ident)
        row.pop("sig", None)
        row["expiry"] = self.expiry
        store.put("delegation_tokens", str(self.ident["token_id"]), row)
        return row


@dataclass
class RenewDelegationToken(OMRequest):
    """Extend a token's renewable expiry, bounded by its max_date
    (OMRenewDelegationTokenRequest; only the named renewer may renew)."""

    token_id: str
    requester: str
    now: float = 0.0
    renew_interval_s: float = 86400.0

    def pre_execute(self, om) -> None:
        self.now = time.time()
        self.renew_interval_s = om.dtoken_renew_interval_s

    def apply(self, store):
        row = store.get("delegation_tokens", self.token_id)
        if row is None:
            raise OMError(TOKEN_ERROR, "token cancelled or unknown")
        if self.requester != row["renewer"]:
            raise OMError(
                TOKEN_ERROR,
                f"{self.requester!r} is not the renewer ({row['renewer']!r})")
        if row["expiry"] < self.now:
            raise OMError(TOKEN_ERROR, "token expired; cannot renew")
        row["expiry"] = round(min(self.now + self.renew_interval_s,
                                  row["max_date"]), 3)
        store.put("delegation_tokens", self.token_id, row)
        return row["expiry"]


@dataclass
class CancelDelegationToken(OMRequest):
    """Invalidate a token (OMCancelDelegationTokenRequest; owner or
    renewer only)."""

    token_id: str
    requester: str

    def apply(self, store):
        row = store.get("delegation_tokens", self.token_id)
        if row is None:
            raise OMError(TOKEN_ERROR, "token cancelled or unknown")
        if self.requester not in (row["owner"], row["renewer"]):
            raise OMError(
                TOKEN_ERROR,
                f"{self.requester!r} is neither owner nor renewer")
        store.delete("delegation_tokens", self.token_id)


@dataclass
class PurgeExpiredDTokens(OMRequest):
    """Background sweep: drop tokens past expiry and master keys that are
    both expired and unreferenced (the reference's ExpiredTokenRemover
    thread inside OzoneDelegationTokenSecretManager)."""

    now: float = 0.0

    def pre_execute(self, om) -> None:
        if not self.now:
            self.now = time.time()

    def apply(self, store):
        dropped = 0
        live_keys = set()
        for tid, row in list(store.iterate("delegation_tokens")):
            if min(row["expiry"], row["max_date"]) < self.now:
                store.delete("delegation_tokens", tid)
                dropped += 1
            else:
                live_keys.add(row["key_id"])
        for kid, row in list(store.iterate("dtoken_keys")):
            if row["expires"] < self.now and kid not in live_keys:
                store.delete("dtoken_keys", kid)
        return dropped
