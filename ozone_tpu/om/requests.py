"""OM write requests: the preExecute / apply split.

Mirrors the reference's OMClientRequest command pattern (ozone-manager
request/OMClientRequest.java:114 preExecute — leader-side normalization and
resource allocation — and :143 validateAndUpdateCache — the deterministic
state mutation applied on every OM replica). Keeping the split means a
consensus layer (Raft) can be inserted later by shipping the post-
preExecute request through a log without rewriting any request logic
(SURVEY.md section 7 step 5).

Each request implements:
  pre_execute(om)  -> may talk to SCM, assign ids/timestamps; returns None
  apply(store)     -> pure function of (request, store); idempotent-safe
  audit fields     -> for the audit log
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ozone_tpu.om.metadata import (
    OMMetadataStore,
    bucket_key,
    key_key,
    volume_key,
)


class OMError(Exception):
    def __init__(self, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}" if msg else code)
        self.code = code
        self.msg = msg  # bare message for re-wrapping without code stacking


VOLUME_NOT_FOUND = "VOLUME_NOT_FOUND"
VOLUME_ALREADY_EXISTS = "VOLUME_ALREADY_EXISTS"
VOLUME_NOT_EMPTY = "VOLUME_NOT_EMPTY"
BUCKET_NOT_FOUND = "BUCKET_NOT_FOUND"
BUCKET_ALREADY_EXISTS = "BUCKET_ALREADY_EXISTS"
BUCKET_NOT_EMPTY = "BUCKET_NOT_EMPTY"
KEY_NOT_FOUND = "KEY_NOT_FOUND"


_REQUEST_TYPES: dict[str, type] = {}


@dataclass
class OMRequest:
    def __init_subclass__(cls, **kw):
        super().__init_subclass__(**kw)
        _REQUEST_TYPES[cls.__name__] = cls

    def pre_execute(self, om: Any) -> None:  # noqa: D401
        """Leader-side phase; default no-op."""

    def apply(self, store: OMMetadataStore) -> Any:
        raise NotImplementedError

    @property
    def audit_action(self) -> str:
        return type(self).__name__

    def to_json(self) -> dict:
        """Wire form for the replicated log (post-preExecute state, so
        followers apply deterministically without re-running preExecute —
        the OMClientRequest contract)."""
        import dataclasses

        return {"type": type(self).__name__, **dataclasses.asdict(self)}

    @staticmethod
    def from_json(d: dict) -> "OMRequest":
        d = dict(d)
        cls = _REQUEST_TYPES[d.pop("type")]
        return cls(**d)


@dataclass
class CreateVolume(OMRequest):
    volume: str
    owner: str = "root"
    quota_bytes: int = -1
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()

    def apply(self, store):
        k = volume_key(self.volume)
        if store.exists("volumes", k):
            raise OMError(VOLUME_ALREADY_EXISTS, self.volume)
        store.put(
            "volumes",
            k,
            {
                "name": self.volume,
                "owner": self.owner,
                "quota_bytes": self.quota_bytes,
                "created": self.created,
            },
        )


@dataclass
class DeleteVolume(OMRequest):
    volume: str

    def apply(self, store):
        k = volume_key(self.volume)
        if not store.exists("volumes", k):
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        if next(store.iterate("buckets", k + "/"), None) is not None:
            raise OMError(VOLUME_NOT_EMPTY, self.volume)
        store.delete("volumes", k)


@dataclass
class CreateBucket(OMRequest):
    volume: str
    bucket: str
    replication: str = "rs-6-3-1024k"
    layout: str = "OBJECT_STORE"
    versioning: bool = False
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()

    def apply(self, store):
        if not store.exists("volumes", volume_key(self.volume)):
            raise OMError(VOLUME_NOT_FOUND, self.volume)
        k = bucket_key(self.volume, self.bucket)
        if store.exists("buckets", k):
            raise OMError(BUCKET_ALREADY_EXISTS, k)
        store.put(
            "buckets",
            k,
            {
                "volume": self.volume,
                "name": self.bucket,
                "replication": self.replication,
                "layout": self.layout,
                "versioning": self.versioning,
                "created": self.created,
            },
        )


@dataclass
class DeleteBucket(OMRequest):
    volume: str
    bucket: str

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        if not store.exists("buckets", k):
            raise OMError(BUCKET_NOT_FOUND, k)
        # FSO buckets keep their namespace in dirs/files, not keys; a
        # detached-but-unpurged subtree still counts as non-empty
        for table in ("keys", "files", "dirs", "deleted_dirs"):
            if next(store.iterate(table, k + "/"), None) is not None:
                raise OMError(BUCKET_NOT_EMPTY, k)
        store.delete("buckets", k)


@dataclass
class CommitKey(OMRequest):
    """Finalize a key: move open-key session state into the key table
    (OMKeyCommitRequest analog)."""

    volume: str
    bucket: str
    key: str
    client_id: str
    size: int
    block_groups: list[dict] = field(default_factory=list)
    replication: str = ""
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    modified: float = 0.0

    def pre_execute(self, om) -> None:
        self.modified = time.time()

    def apply(self, store):
        kk = key_key(self.volume, self.bucket, self.key)
        open_k = f"{kk}/{self.client_id}"
        if not store.exists("open_keys", open_k):
            raise OMError(KEY_NOT_FOUND, f"no open session {open_k}")
        info = store.get("open_keys", open_k)
        info.update(
            {
                "size": self.size,
                "block_groups": self.block_groups,
                "modified": self.modified,
            }
        )
        store.delete("open_keys", open_k)
        # overwrite: the previous version's blocks must reach the purge
        # chain or they leak on the datanodes
        old = store.get("keys", kk)
        if old is not None and old.get("block_groups"):
            store.put("deleted_keys", f"{kk}:{self.modified}", old)
        store.put("keys", kk, info)
        return info


@dataclass
class OpenKey(OMRequest):
    """Record an open-key session (OMKeyCreateRequest analog — block
    allocation happens in pre_execute via SCM, like the reference's
    preExecute asking SCM for blocks)."""

    volume: str
    bucket: str
    key: str
    client_id: str
    replication: str
    checksum_type: str = "CRC32C"
    bytes_per_checksum: int = 16 * 1024
    created: float = 0.0

    def pre_execute(self, om) -> None:
        self.created = time.time()

    def apply(self, store):
        if not store.exists("buckets", bucket_key(self.volume, self.bucket)):
            raise OMError(BUCKET_NOT_FOUND, f"{self.volume}/{self.bucket}")
        kk = key_key(self.volume, self.bucket, self.key)
        store.put(
            "open_keys",
            f"{kk}/{self.client_id}",
            {
                "volume": self.volume,
                "bucket": self.bucket,
                "name": self.key,
                "replication": self.replication,
                "checksum_type": self.checksum_type,
                "bytes_per_checksum": self.bytes_per_checksum,
                "size": 0,
                "block_groups": [],
                "created": self.created,
                "modified": self.created,
            },
        )


@dataclass
class DeleteKey(OMRequest):
    """Move a key to the deleted table for async purge (OMKeyDeleteRequest +
    KeyDeletingService pattern)."""

    volume: str
    bucket: str
    key: str
    ts: float = 0.0

    def pre_execute(self, om) -> None:
        self.ts = time.time()

    def apply(self, store):
        kk = key_key(self.volume, self.bucket, self.key)
        info = store.get("keys", kk)
        if info is None:
            raise OMError(KEY_NOT_FOUND, kk)
        store.delete("keys", kk)
        store.put("deleted_keys", f"{kk}:{self.ts}", info)
        return info


@dataclass
class RenameKey(OMRequest):
    volume: str
    bucket: str
    key: str
    new_key: str

    def apply(self, store):
        src = key_key(self.volume, self.bucket, self.key)
        info = store.get("keys", src)
        if info is None:
            raise OMError(KEY_NOT_FOUND, src)
        dst = key_key(self.volume, self.bucket, self.new_key)
        info["name"] = self.new_key
        store.delete("keys", src)
        store.put("keys", dst, info)


@dataclass
class SetS3Secret(OMRequest):
    """Store an access-id's S3 secret (reference: S3GetSecretRequest
    creates on first fetch; OMSetSecretRequest overwrites). With
    if_absent, the get-or-create is atomic inside apply so concurrent
    first fetches converge on one secret."""

    access_id: str
    secret: str
    if_absent: bool = False

    def apply(self, store):
        if self.if_absent:
            row = store.get("s3_secrets", self.access_id)
            if row is not None:
                return row["secret"]
        store.put(
            "s3_secrets", self.access_id,
            {"access_id": self.access_id, "secret": self.secret},
        )
        return self.secret


@dataclass
class RevokeS3Secret(OMRequest):
    access_id: str

    def apply(self, store):
        store.delete("s3_secrets", self.access_id)


@dataclass
class SetBucketAcl(OMRequest):
    """Replace a bucket's ACL grant list (reference: OMBucketSetAclRequest;
    S3 grants map onto the bucket record)."""

    volume: str
    bucket: str
    acl: list[dict] = field(default_factory=list)

    def apply(self, store):
        k = bucket_key(self.volume, self.bucket)
        b = store.get("buckets", k)
        if b is None:
            raise OMError(BUCKET_NOT_FOUND, k)
        b["acl"] = self.acl
        store.put("buckets", k, b)


@dataclass
class PurgeDeletedKeys(OMRequest):
    """Remove processed entries from the deleted table (background
    KeyDeletingService completion)."""

    entries: list[str] = field(default_factory=list)

    def apply(self, store):
        for k in self.entries:
            store.delete("deleted_keys", k)
