"""Sharded metadata plane: hash-partitioned OM rings under a root map.

Layout (ROADMAP open item 3; Azure Storage ATC '12 partition layer +
f4 OSDI '14 off-leader reads, applied to the jax_graft OM):

- `shardmap.py`  — slot hashing, the epoch-numbered root shard map,
                   per-shard replicated ownership config, SHARD_MOVED.
- `txn.py`       — two-phase cross-shard rename / bucket link with a
                   root-ring coordinator journal and crash recovery.
- `leases.py`    — lease-based follower reads (gate + knobs).
- `plane.py`     — in-process sharded plane + ShardedOm facade
                   (minicluster boot, bench, failure drills).
- `router.py`    — client-side shard-map cache and routing.

Importing this package registers the sharding OMRequest subclasses, so
any process that may APPLY replicated sharding entries (daemons,
followers) must import it before its first log replay — daemons.py does
this at module import.
"""

from ozone_tpu.utils.metrics import registry

#: the om.shard.* observability family (pinned in test_observability)
METRICS = registry("om.shard")

# request registration side effects (OMRequest.__init_subclass__)
from ozone_tpu.om.sharding import leases, shardmap, txn  # noqa: E402,F401
from ozone_tpu.om.sharding.shardmap import (  # noqa: E402
    SHARD_MOVED,
    SLOT_COUNT,
    ShardMap,
    slot_for,
)

__all__ = ["METRICS", "SHARD_MOVED", "SLOT_COUNT", "ShardMap",
           "slot_for", "leases", "shardmap", "txn"]
