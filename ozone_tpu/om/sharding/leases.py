"""Lease-based follower reads for the metadata plane.

A follower that heard from its leader within the read-lease window may
serve read-only verbs locally (the f4 OSDI '14 shape: read-dominant
traffic must leave the leader). Correctness rests on two bounds:

- **Staleness is bounded by the lease**: the lease window is shorter
  than the minimum election timeout, so while a follower's lease is
  live no OTHER node can have won an election and committed writes the
  follower has never heard of. Once the lease lapses the follower
  refuses and the client falls back to the leader.
- **Read-your-writes is bounded by `min_applied`**: clients thread the
  highest applied index they have observed through their reads; a
  follower whose state machine lags that index refuses rather than
  serve an older view.
"""

from __future__ import annotations

import os
from typing import Optional

from ozone_tpu.utils.metrics import registry

METRICS = registry("om.shard")

#: read-only OM verbs a lease-holding follower may answer. Everything
#: else (writes, anything that allocates) must reach the leader.
FOLLOWER_READ_VERBS = frozenset({
    "LookupKey", "ListKeys", "ListKeysPaged", "BucketInfo",
    "ListBuckets", "VolumeInfo", "ListVolumes", "GetFileStatus",
    "ListStatus", "KeyBlockGroups", "GetShardMap",
})


def lease_duration_s() -> float:
    """OZONE_TPU_OM_LEASE_S: follower read-lease window. Default stays
    under the 0.15 s minimum election timeout — a longer lease than
    that re-introduces the stale-read race the lease exists to close."""
    return float(os.environ.get("OZONE_TPU_OM_LEASE_S", "0.12"))


def follower_reads_enabled() -> bool:
    """OZONE_TPU_OM_FOLLOWER_READS=1: clients prefer follower replicas
    for the read verbs above. Off by default — an unsharded deployment
    keeps strict leader reads unless the operator opts in."""
    return os.environ.get("OZONE_TPU_OM_FOLLOWER_READS", "0") == "1"


class FollowerReadGate:
    """Per-replica admission check for follower reads, shared by the
    gRPC daemon gate and the in-process sharded plane.

    `try_serve` answers: may THIS replica answer `verb` right now,
    given the client has already observed `min_applied`?"""

    def __init__(self, node, lease_s: Optional[float] = None,
                 metrics=METRICS):
        self.node = node  # consensus.raft.RaftNode
        self.lease_s = lease_duration_s() if lease_s is None else lease_s
        self.metrics = metrics

    def try_serve(self, verb: str, min_applied: int = 0) -> bool:
        if verb not in FOLLOWER_READ_VERBS:
            return False
        if not self.node.follower_lease_valid(self.lease_s):
            self.metrics.counter("follower_read_misses").inc()
            return False
        if self.node.last_applied < int(min_applied or 0):
            # lease is live but the state machine lags what the client
            # has already seen: refuse rather than time-travel
            self.metrics.counter("follower_read_misses").inc()
            return False
        self.metrics.counter("follower_read_hits").inc()
        return True
