"""In-process sharded metadata plane: N shard rings + a root map.

Two modes, both used by the minicluster, the bench, and the failure
drills:

- ``mode="plain"``: each shard is ONE OzoneManager over its own store
  (no raft), all sharing the caller's SCM + datanode clients. This is
  the shard-scaling bench shape — independent stores mean independent
  sqlite WAL fsyncs, so meta ops/s scales with ring count — and the
  crash-recovery drill shape (a coordinator "kill -9" leaves exactly
  the journal + intent rows a dead process would).
- ``mode="ring"``: each shard is a `replicas`-node MetaHARing over an
  InProcessTransport. This is the kill-the-leader drill shape and the
  follower-read shape (every replica holds a read lease off the
  leader's heartbeats).

The ROOT ring is the degenerate single-replica form here (one
OzoneManager store holding the shard map and the 2PC coordinator
journal); the daemon deployment replicates it like any other ring.

`ShardedOm` is the facade the rest of the stack talks to: it exposes
the OzoneManager surface (`OzoneClient(facade, clients)` and freon both
work unchanged), routes every (volume, bucket) op to the owning shard
via the cached shard map, retries once through a map refresh on
`SHARD_MOVED`, resolves bucket-link chains ACROSS shards (a per-shard
OM can only follow local links), fans volume ops out to every shard,
and drives cross-bucket renames / cross-shard links through the 2PC
coordinator.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Callable, Optional

from ozone_tpu.consensus.meta_ring import MetaHARing
from ozone_tpu.consensus.raft import InProcessTransport, NotRaftLeaderError
from ozone_tpu.om import requests as rq
from ozone_tpu.om.metadata import bucket_key
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.om.sharding.shardmap import (
    SHARD_MOVED,
    SLOT_COUNT,
    ImportRow,
    InstallShardConfig,
    InstallShardMap,
    ShardMap,
    slot_for,
)
from ozone_tpu.om.sharding.txn import CrossShardCoordinator
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.utils.metrics import registry

METRICS = registry("om.shard")

#: tables copied when a slot migrates between shards (key-bearing
#: tables are prefix-scanned per bucket; FSO tables ride the same
#: bucket_key prefix scheme)
_MIGRATE_TABLES = ("buckets", "keys", "open_keys", "deleted_keys",
                   "multipart", "dirs", "files", "deleted_dirs",
                   "slabs")


def _meta_scm() -> StorageContainerManager:
    """A liveness-quiet SCM for metadata-only shard replicas (no
    datanodes register with it; block ops use the shared data SCM)."""
    return StorageContainerManager(stale_after_s=1e6, dead_after_s=2e6)


class _Shard:
    """One shard: a plain OzoneManager or a ring of replicas."""

    def __init__(self, shard_id: str, base: Path, mode: str,
                 scm=None, clients=None, replicas: int = 3,
                 timers: bool = True, push_commit: bool = False):
        self.id = shard_id
        self.mode = mode
        self.replicas: list[MetaHARing] = []
        #: highest applied index this plane has OBSERVED on the shard's
        #: leader after a write — the facade's read-your-writes floor
        self.applied_floor = 0
        if mode == "plain":
            self.plain_om = OzoneManager(base / "om.db",
                                         scm or _meta_scm(), clients)
            self.transport = None
            return
        self.plain_om = None
        self.transport = InProcessTransport()
        self._timers = timers
        ids = [f"{shard_id}-r{i}" for i in range(replicas)]
        for nid in ids:
            rep_scm = _meta_scm()
            rep_om = OzoneManager(base / nid / "om.db", rep_scm, clients)
            ring = MetaHARing(rep_om, rep_scm, base / nid / "raft",
                              nid, ids, transport=self.transport)
            # fresh commit index on every write: follower leases serve
            # reads within min_applied immediately, not a heartbeat
            # late. Only when follower reads are on — the extra
            # replication round is pure overhead for write-only rings.
            ring.push_commit_on_write = push_commit
            # writes reaching this replica's om go through the ring
            # (the daemons.py _init_ha patch, in-process form)
            rep_om.submit = ring.submit_om
            self.replicas.append(ring)
        if timers:
            for r in self.replicas:
                r.node.start_timers()
        else:
            self.replicas[0].node.start_election()

    # -- leadership ----------------------------------------------------
    def leader(self) -> Optional[MetaHARing]:
        for r in self.replicas:
            # a killed leader keeps its LEADER role (a dead process
            # can't demote itself) — the transport's down-set is truth
            if r.node.node_id in self.transport.down:
                continue
            if r.is_ready:
                return r
        return None

    def await_leader(self, timeout: float = 5.0) -> MetaHARing:
        deadline = time.monotonic() + timeout
        while True:
            r = self.leader()
            if r is not None:
                return r
            if time.monotonic() >= deadline:
                raise TimeoutError(f"shard {self.id}: no ready leader")
            if not self._timers:
                for cand in self.replicas:
                    if cand.node.node_id not in self.transport.down:
                        cand.node.start_election()
            # ozlint: allow[deadline-propagation] -- fixed 10ms election
            # poll inside the explicit `timeout` deadline loop above
            time.sleep(0.01)

    @property
    def om(self) -> OzoneManager:
        """The authoritative (leader) OM for this shard."""
        if self.mode == "plain":
            return self.plain_om
        return self.await_leader().om

    def submit(self, request: rq.OMRequest) -> Any:
        if self.mode == "plain":
            return self.plain_om.submit(request)
        err: Exception = TimeoutError(f"shard {self.id} unavailable")
        for _ in range(3):
            try:
                ring = self.await_leader()
                result = ring.submit_om(request)
                self.applied_floor = ring.node.last_applied
                return result
            except NotRaftLeaderError as e:
                err = e  # deposed between await and submit: re-resolve
        raise err

    # -- failure injection --------------------------------------------
    def kill_leader(self) -> str:
        """kill -9 the shard leader: its node stops mid-flight and the
        transport drops it, exactly as a dead process looks to peers."""
        ring = self.await_leader()
        nid = ring.node.node_id
        self.transport.down.add(nid)
        ring.node.stop()
        return nid

    def close(self) -> None:
        for r in self.replicas:
            r.node.stop()
            r.om.store.close()
        if self.plain_om is not None:
            self.plain_om.store.close()


class ShardedMetaPlane:
    """Boot + operate a sharded metadata plane in one process."""

    def __init__(self, base_dir: Path, n_shards: int = 2,
                 mode: str = "plain", replicas: int = 3,
                 scm=None, clients=None, timers: bool = True,
                 follower_reads: bool = False,
                 slot_count: int = SLOT_COUNT):
        base = Path(base_dir)
        self.mode = mode
        self.follower_reads = follower_reads and mode == "ring"
        # the root ring (degenerate single replica): shard map + journal
        self.root = OzoneManager(base / "root" / "om.db",
                                 scm or _meta_scm())
        self.shard_ids = [f"s{i}" for i in range(n_shards)]
        self.shards = {
            sid: _Shard(sid, base / sid, mode, scm=scm, clients=clients,
                        replicas=replicas, timers=timers,
                        push_commit=self.follower_reads)
            for sid in self.shard_ids
        }
        m = ShardMap.uniform(self.shard_ids, epoch=1,
                             slot_count=slot_count)
        self.install_map(m)
        self.coordinator = CrossShardCoordinator(
            self.root.submit,
            lambda sid, request: self.shards[sid].submit(request),
            self.root.store,
            self.current_map,
        )
        self.facade = ShardedOm(self)

    # -- shard map -----------------------------------------------------
    def current_map(self) -> ShardMap:
        row = self.root.store.get("system", "shard_map")
        return ShardMap.from_json(row)

    def install_map(self, m: ShardMap) -> None:
        """Publish a map epoch: per-shard replicated ownership configs
        first (enforcement), then the root row (discovery)."""
        for sid in m.shards:
            self.shards[sid].submit(InstallShardConfig(
                epoch=m.epoch, shard_id=sid,
                slot_count=m.slot_count, owned=m.owned_slots(sid)))
        self.root.submit(InstallShardMap(m.to_json()))

    def migrate_slot(self, slot: int, to_shard: str) -> ShardMap:
        """Rebalance one slot (docs/OPERATIONS.md runbook): fence the
        source (it starts rejecting the slot with SHARD_MOVED), copy
        the slot's rows, grant the target, publish the bumped map.
        Requests racing the window bounce off BOTH sides and retry
        through the refreshed map."""
        m = self.current_map()
        from_shard = m.shards[m.slots[slot]]
        if from_shard == to_shard:
            return m
        new_map = m.move_slot(slot, to_shard)
        src, dst = self.shards[from_shard], self.shards[to_shard]
        src.submit(InstallShardConfig(
            epoch=new_map.epoch, shard_id=from_shard,
            slot_count=new_map.slot_count,
            owned=new_map.owned_slots(from_shard)))
        self._copy_slot_rows(slot, src.om.store, dst)
        dst.submit(InstallShardConfig(
            epoch=new_map.epoch, shard_id=to_shard,
            slot_count=new_map.slot_count,
            owned=new_map.owned_slots(to_shard)))
        self.root.submit(InstallShardMap(new_map.to_json()))
        METRICS.counter("slots_migrated").inc()
        return new_map

    def _copy_slot_rows(self, slot: int, src_store, dst: _Shard) -> None:
        # volumes exist on every shard already (fan-out create); move
        # the slot's bucket-scoped rows via replicated raw imports
        for vk, _ in list(src_store.iterate("volumes")):
            for bk, brow in list(src_store.iterate("buckets", vk + "/")):
                vol, bkt = brow["volume"], brow["name"]
                if slot_for(vol, bkt, self.current_map().slot_count) \
                        != slot:
                    continue
                dst.submit(ImportRow("buckets", bk, brow))
                for table in _MIGRATE_TABLES[1:]:
                    prefix = bucket_key(vol, bkt) + "/"
                    for k, row in list(src_store.iterate(table, prefix)):
                        dst.submit(ImportRow(table, k, row))

    def recover(self) -> list[dict]:
        """Re-drive open cross-shard transactions after a crash."""
        return self.coordinator.recover()

    def client(self, clients=None):
        """An OzoneClient over the sharded facade (full datapath when
        `clients` is the data plane's DatanodeClientFactory)."""
        from ozone_tpu.client.ozone_client import OzoneClient

        return OzoneClient(self.facade, clients)

    def close(self) -> None:
        for shard in self.shards.values():
            shard.close()
        self.root.store.close()


class ShardedOm:
    """OzoneManager-surface facade routing by the cached shard map."""

    def __init__(self, plane: ShardedMetaPlane):
        self._plane = plane
        self._map = plane.current_map()
        self._rr = 0  # follower round-robin cursor
        self.metrics = METRICS

    # -- plumbing ------------------------------------------------------
    @property
    def block_size(self) -> int:
        return self._shard0.om.block_size

    @property
    def _shard0(self) -> _Shard:
        return self._plane.shards[self._plane.shard_ids[0]]

    def _read_om(self, shard: _Shard, verb: str,
                 min_applied: Optional[int] = None) -> OzoneManager:
        """Pick the replica to serve a read: a lease-holding follower
        when enabled and fresh enough, else the leader."""
        if shard.mode == "ring" and self._plane.follower_reads:
            floor = shard.applied_floor if min_applied is None \
                else min_applied
            n = len(shard.replicas)
            for k in range(n):
                r = shard.replicas[(self._rr + k) % n]
                if r.node.is_leader or \
                        r.node.node_id in shard.transport.down:
                    continue
                if r.read_gate.try_serve(verb, floor):
                    self._rr = (self._rr + k + 1) % n
                    return r.om
        return shard.om

    def _routed(self, verb: str, volume: str, bucket: str,
                fn: Callable[[OzoneManager], Any],
                write: bool = False) -> Any:
        """Route fn to the owning shard; one SHARD_MOVED retry through
        a root-map refresh (the client-side cache invalidation)."""
        for attempt in (0, 1):
            sid = self._map.shard_for(volume, bucket)
            shard = self._plane.shards[sid]
            self.metrics.counter("routes").inc()
            try:
                om = shard.om if write else self._read_om(shard, verb)
                return fn(om)
            except rq.OMError as e:
                if e.code == SHARD_MOVED and attempt == 0:
                    self.metrics.counter("moved_rejections").inc()
                    self._map = self._plane.current_map()
                    continue
                raise
        raise AssertionError("unreachable")  # pragma: no cover

    # -- volumes (fan-out: every shard owns buckets of any volume) -----
    def create_volume(self, volume: str, owner: str = "root") -> None:
        for sid in self._plane.shard_ids:
            self._plane.shards[sid].submit(rq.CreateVolume(volume, owner))

    def delete_volume(self, volume: str) -> None:
        for sid in self._plane.shard_ids:  # check-all THEN delete-all
            om = self._plane.shards[sid].om
            if om.list_buckets(volume):
                raise rq.OMError(rq.VOLUME_NOT_EMPTY, volume)
        for sid in self._plane.shard_ids:
            self._plane.shards[sid].submit(rq.DeleteVolume(volume))

    def volume_info(self, volume: str) -> dict:
        return self._read_om(self._shard0, "VolumeInfo").volume_info(
            volume)

    def list_volumes(self) -> list[dict]:
        return self._read_om(self._shard0, "ListVolumes").list_volumes()

    # -- buckets -------------------------------------------------------
    def create_bucket(self, volume: str, bucket: str, *a, **kw) -> None:
        self._routed("CreateBucket", volume, bucket,
                     lambda om: om.create_bucket(volume, bucket,
                                                 *a, **kw),
                     write=True)

    def create_bucket_link(self, src_volume: str, src_bucket: str,
                           volume: str, bucket: str) -> None:
        if self._map.shard_for(src_volume, src_bucket) == \
                self._map.shard_for(volume, bucket):
            self._routed(
                "CreateBucket", volume, bucket,
                lambda om: om.create_bucket_link(
                    src_volume, src_bucket, volume, bucket),
                write=True)
            return
        # source validated on ITS shard, link staged on the link's own
        # shard, both committed under the root journal
        self._plane.coordinator.link_bucket_cross(rq.CreateBucket(
            volume, bucket, created=time.time(),
            source_volume=src_volume, source_bucket=src_bucket))

    def delete_bucket(self, volume: str, bucket: str) -> None:
        self._routed("DeleteBucket", volume, bucket,
                     lambda om: om.delete_bucket(volume, bucket),
                     write=True)

    def bucket_info(self, volume: str, bucket: str) -> dict:
        # raw-row read + facade-side link resolution: a per-shard OM
        # cannot follow a link whose source lives on another shard
        b = self._routed(
            "BucketInfo", volume, bucket,
            lambda om: om.store.get("buckets",
                                    bucket_key(volume, bucket)))
        if b is None:
            raise rq.OMError(rq.BUCKET_NOT_FOUND, f"{volume}/{bucket}")
        if b.get("source"):
            rv, rb = self.resolve_bucket(volume, bucket)
            eff = self._routed(
                "BucketInfo", rv, rb,
                lambda om: om.store.get("buckets",
                                        bucket_key(rv, rb))) or {}
            b = dict(b)
            b["replication"] = eff.get("replication", b["replication"])
            b["layout"] = eff.get("layout", b["layout"])
        return b

    def list_buckets(self, volume: str) -> list[dict]:
        out: list[dict] = []
        for sid in self._plane.shard_ids:
            shard = self._plane.shards[sid]
            om = self._read_om(shard, "ListBuckets")
            out.extend(om.list_buckets(volume))
        return sorted(out, key=lambda b: b["name"])

    def resolve_bucket(self, volume: str, bucket: str) -> tuple[str, str]:
        """Cross-shard link-chain resolution (OzoneManager
        .resolve_bucket semantics, but each hop routed to its owner)."""
        seen: set = set()
        while True:
            row = self._routed(
                "BucketInfo", volume, bucket,
                lambda om, v=volume, b=bucket:
                    om.store.get("buckets", bucket_key(v, b)))
            if row is None:
                if seen:
                    raise rq.OMError(rq.DANGLING_LINK,
                                     f"{volume}/{bucket} missing")
                raise rq.OMError(rq.BUCKET_NOT_FOUND,
                                 f"{volume}/{bucket}")
            src = row.get("source")
            if not src:
                return volume, bucket
            if (volume, bucket) in seen:
                raise rq.OMError(rq.DANGLING_LINK,
                                 f"link loop at {volume}/{bucket}")
            seen.add((volume, bucket))
            volume, bucket = src["volume"], src["bucket"]

    # -- keys ----------------------------------------------------------
    def open_key(self, volume: str, bucket: str, key: str, *a, **kw):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "OpenKey", rv, rb,
            lambda om: om.open_key(rv, rb, key, *a, **kw), write=True)

    def allocate_block(self, session, *a, **kw):
        return self._routed(
            "AllocateBlock", session.volume, session.bucket,
            lambda om: om.allocate_block(session, *a, **kw), write=True)

    def commit_key(self, session, groups, size, hsync: bool = False):
        return self._routed(
            "CommitKey", session.volume, session.bucket,
            lambda om: om.commit_key(session, groups, size, hsync),
            write=True)

    def lookup_key(self, volume: str, bucket: str, key: str) -> dict:
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed("LookupKey", rv, rb,
                            lambda om: om.lookup_key(rv, rb, key))

    # small-object verbs: slabs are bucket-scoped rows, so a batched
    # CommitKeys — N needles + the slab directory — lands on exactly
    # ONE shard ring as one entry (the whole point of the batching)
    def set_bucket_smallobj(self, volume: str, bucket: str, *a, **kw):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "SetBucketSmallObj", rv, rb,
            lambda om: om.set_bucket_smallobj(rv, rb, *a, **kw),
            write=True)

    def put_inline_key(self, volume: str, bucket: str, key: str,
                       data, **kw):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "PutInlineKey", rv, rb,
            lambda om: om.put_inline_key(rv, rb, key, data, **kw),
            write=True)

    def commit_keys(self, volume: str, bucket: str, slab: dict,
                    entries: list):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "CommitKeys", rv, rb,
            lambda om: om.commit_keys(rv, rb, slab, entries),
            write=True)

    def slab_info(self, volume: str, bucket: str, slab_id: str) -> dict:
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "SlabInfo", rv, rb,
            lambda om: om.slab_info(rv, rb, slab_id))

    def list_slabs(self, volume: str, bucket: str) -> list:
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "ListSlabs", rv, rb,
            lambda om: om.list_slabs(rv, rb))

    def list_keys(self, volume: str, bucket: str, *a, **kw):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed("ListKeys", rv, rb,
                            lambda om: om.list_keys(rv, rb, *a, **kw))

    def delete_key(self, volume: str, bucket: str, key: str, *a, **kw):
        rv, rb = self.resolve_bucket(volume, bucket)
        return self._routed(
            "DeleteKey", rv, rb,
            lambda om: om.delete_key(rv, rb, key, *a, **kw), write=True)

    def rename_key(self, volume: str, bucket: str, key: str,
                   new_key: str) -> None:
        rv, rb = self.resolve_bucket(volume, bucket)
        self._routed("RenameKey", rv, rb,
                     lambda om: om.rename_key(rv, rb, key, new_key),
                     write=True)

    def rename_key_cross(self, volume: str, src_bucket: str, key: str,
                         dst_bucket: str, new_key: str) -> dict:
        """Cross-BUCKET rename (the op that can span shards): always
        the 2PC — same-shard pairs just run both halves on one ring."""
        rv, rb = self.resolve_bucket(volume, src_bucket)
        dv, db = self.resolve_bucket(volume, dst_bucket)
        if rv != dv:
            raise rq.OMError(rq.INVALID_REQUEST,
                             "cross-volume rename is not supported")
        return self._plane.coordinator.rename_cross(
            rv, rb, key, db, new_key)

    def key_block_groups(self, info: dict):
        return self._shard0.om.key_block_groups(info)

    # -- everything else: shard-0 leader (kms, tokens, snapshots …) ----
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._shard0.om, name)
