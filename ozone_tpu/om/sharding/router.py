"""Client-side shard routing: cached map, per-shard pools, floors.

GrpcOmClient discovers the shard map once (GetShardMap, served ungated
by any replica), builds one FailoverChannels pool per shard from the
map's address book, and routes every bucket-addressed verb to the
owning shard. The map is a CACHE: a `SHARD_MOVED` rejection from a
shard that no longer owns the slot invalidates it — the client
refetches the map and retries once through the new owner.

The router also tracks a per-shard applied-index floor (the highest
`_applied` seen in any response) so lease-based follower reads can
carry `_min_applied`: a follower whose state machine lags the caller's
own writes refuses and the read falls back to the leader.
"""

from __future__ import annotations

import threading
from typing import Optional

from ozone_tpu.om.sharding.leases import (
    FOLLOWER_READ_VERBS,
    follower_reads_enabled,
)
from ozone_tpu.om.sharding.shardmap import ShardMap
from ozone_tpu.utils.metrics import registry

METRICS = registry("om.shard")

#: verbs never routed by (volume, bucket) even when both are present:
#: KMS state lives in the home OM's store, not the bucket's shard
ROUTE_EXEMPT = frozenset({"GetShardMap", "KmsDecrypt", "KmsCreateKey",
                          "KmsKeyInfo", "KmsListKeys"})


class ShardRouter:
    """The client half of the shard map: routing + invalidation."""

    def __init__(self, map_json: dict, tls=None):
        from ozone_tpu.net.rpc import FailoverChannels

        self._tls = tls
        self._lock = threading.Lock()
        self.map = ShardMap.from_json(map_json)
        self.pools: dict[str, "FailoverChannels"] = {}
        self._floors: dict[str, int] = {}
        self._read_rr: dict[str, int] = {}
        self._build_pools(FailoverChannels)

    def _build_pools(self, FailoverChannels) -> None:
        for sid, addrs in self.map.addresses.items():
            if addrs and sid not in self.pools:
                self.pools[sid] = FailoverChannels(addrs, tls=self._tls)

    @property
    def routable(self) -> bool:
        return bool(self.pools)

    def route(self, method: str, meta: dict):
        """(shard_id, pool) for a routable call, else (None, None)."""
        volume, bucket = meta.get("volume"), meta.get("bucket")
        if not volume or not bucket or method in ROUTE_EXEMPT:
            return None, None
        sid = self.map.shard_for(volume, bucket)
        pool = self.pools.get(sid)
        if pool is None:
            return None, None
        METRICS.counter("routes").inc()
        if follower_reads_enabled() and method in FOLLOWER_READ_VERBS:
            meta["_min_applied"] = self.floor(sid)
        return sid, pool

    def read_address(self, sid: str) -> Optional[str]:
        """Round-robin follower preference for lease-served reads (the
        leader answers too if the cursor lands on it — it is simply a
        leader read then)."""
        pool = self.pools.get(sid)
        if pool is None or len(pool.addresses) < 2:
            return None
        with self._lock:
            i = self._read_rr.get(sid, 0)
            self._read_rr[sid] = i + 1
        return pool.addresses[i % len(pool.addresses)]

    def observe(self, sid: Optional[str], resp: dict) -> None:
        """Advance the shard's applied floor from a response."""
        idx = resp.get("_applied")
        if sid is None or not isinstance(idx, int):
            return
        with self._lock:
            if idx > self._floors.get(sid, 0):
                self._floors[sid] = idx

    def floor(self, sid: str) -> int:
        with self._lock:
            return self._floors.get(sid, 0)

    def update_map(self, map_json: dict) -> None:
        """Adopt a refreshed map (SHARD_MOVED invalidation). Pools for
        shards whose address list is unchanged are REUSED — their
        channels may carry in-flight calls on other threads."""
        from ozone_tpu.net.rpc import FailoverChannels

        new = ShardMap.from_json(map_json)
        with self._lock:
            for sid, addrs in new.addresses.items():
                old = self.map.addresses.get(sid)
                if sid in self.pools and old != addrs:
                    self.pools.pop(sid)
            self.map = new
        self._build_pools(FailoverChannels)

    def close(self) -> None:
        for pool in self.pools.values():
            pool.close()
