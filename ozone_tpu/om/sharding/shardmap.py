"""Shard map: the root ring's partition table for the sharded OM plane.

The namespace is hash-partitioned across N independent meta rings by
(volume, bucket): `crc32(volume/bucket) % SLOT_COUNT` picks one of a
fixed number of slots, and the epoch-numbered shard map assigns every
slot to exactly one shard. The map lives in the ROOT ring (the Azure
Storage ATC '12 shape: a small partition map over many range/hash
partitions); clients cache it and refresh on a `SHARD_MOVED` rejection.

Ownership is enforced server-side, not trusted client-side: every shard
replica carries its own replicated `system/shard_config` row (installed
through its ring, so followers converge with the log) and rejects any
bucket-addressed request whose slot it does not own. A stale client map
therefore cannot read or write through a moved slot — the rejection IS
the cache-invalidation signal.
"""

from __future__ import annotations

import zlib
from dataclasses import dataclass, field
from typing import Optional

from ozone_tpu.om.requests import INVALID_REQUEST, OMError, OMRequest

#: fixed slot count: small enough that the map is a trivial row, large
#: enough to rebalance in slot-granular moves (64 slots over <= 16
#: shards keeps every shard within 1 slot of the mean)
SLOT_COUNT = 64

#: rejection code for a request that landed on a shard that does not
#: own the (volume, bucket) slot — the message carries the rejecting
#: replica's config epoch so clients can tell stale-map from split-brain
SHARD_MOVED = "SHARD_MOVED"


def slot_for(volume: str, bucket: str, slot_count: int = SLOT_COUNT) -> int:
    """Stable slot for a (volume, bucket) pair. crc32 — not hash() — so
    every process, replica, and client agrees across restarts."""
    return zlib.crc32(f"{volume}/{bucket}".encode()) % slot_count


@dataclass
class ShardMap:
    """Epoch-numbered slot -> shard assignment (the root ring row)."""

    epoch: int
    shards: list[str]  # shard ids, index = slot value domain
    slots: list[int] = field(default_factory=list)  # slot -> shards idx
    #: shard id -> comma-joined "host:port,host:port" replica list
    #: (empty for in-process planes that route by object, not address)
    addresses: dict[str, str] = field(default_factory=dict)

    @classmethod
    def uniform(cls, shards: list[str], epoch: int = 1,
                addresses: Optional[dict[str, str]] = None,
                slot_count: int = SLOT_COUNT) -> "ShardMap":
        """Round-robin every slot over the shard list."""
        return cls(
            epoch=epoch,
            shards=list(shards),
            slots=[i % len(shards) for i in range(slot_count)],
            addresses=dict(addresses or {}),
        )

    @property
    def slot_count(self) -> int:
        return len(self.slots)

    def shard_for(self, volume: str, bucket: str) -> str:
        return self.shards[self.slots[slot_for(volume, bucket,
                                               len(self.slots))]]

    def owned_slots(self, shard_id: str) -> list[int]:
        idx = self.shards.index(shard_id)
        return [s for s, owner in enumerate(self.slots) if owner == idx]

    def move_slot(self, slot: int, shard_id: str) -> "ShardMap":
        """A rebalance step: reassign one slot, bump the epoch."""
        slots = list(self.slots)
        slots[slot] = self.shards.index(shard_id)
        return ShardMap(epoch=self.epoch + 1, shards=list(self.shards),
                        slots=slots, addresses=dict(self.addresses))

    def to_json(self) -> dict:
        return {"epoch": self.epoch, "shards": list(self.shards),
                "slots": list(self.slots),
                "addresses": dict(self.addresses)}

    @classmethod
    def from_json(cls, d: dict) -> "ShardMap":
        return cls(epoch=d["epoch"], shards=list(d["shards"]),
                   slots=list(d["slots"]),
                   addresses=dict(d.get("addresses") or {}))


@dataclass
class InstallShardMap(OMRequest):
    """Root-ring request: publish a new shard map (replicated, so every
    root replica serves the same map at the same epoch)."""

    map_json: dict

    def apply(self, store):
        cur = store.get("system", "shard_map")
        if cur is not None and self.map_json["epoch"] <= cur["epoch"]:
            if self.map_json == cur:
                return cur  # idempotent re-install (log replay)
            raise OMError(
                INVALID_REQUEST,
                f"shard map epoch {self.map_json['epoch']} <= "
                f"current {cur['epoch']}")
        store.put("system", "shard_map", dict(self.map_json))
        return dict(self.map_json)


@dataclass
class InstallShardConfig(OMRequest):
    """Per-shard-ring request: record which slots THIS ring owns.

    Replicated through the shard's own ring so followers enforce the
    same ownership set as the leader; the epoch guard makes a delayed
    re-install of an older assignment a no-op rather than a regression.
    """

    epoch: int
    shard_id: str
    slot_count: int
    owned: list[int]  # slots this shard serves

    def apply(self, store):
        cur = store.get("system", "shard_config")
        if cur is not None and self.epoch < cur["epoch"]:
            raise OMError(
                INVALID_REQUEST,
                f"shard config epoch {self.epoch} < current "
                f"{cur['epoch']}")
        row = {"epoch": self.epoch, "shard_id": self.shard_id,
               "slot_count": self.slot_count,
               "owned": sorted(self.owned)}
        store.put("system", "shard_config", row)
        return row


@dataclass
class ImportRow(OMRequest):
    """Slot migration: replicated raw-row import on the RECEIVING ring.

    Used only by the rebalance runbook (plane.migrate_slot / operator
    tooling) while the slot is fenced on both sides, so a verbatim put
    is safe — the source ring already rejects writes to the slot and
    the row set being copied is quiescent.
    """

    table: str
    key: str
    row: dict

    def apply(self, store):
        store.put(self.table, self.key, dict(self.row))


def check_shard(store, volume: str, bucket: str) -> None:
    """Server-side ownership gate: raise SHARD_MOVED when this replica's
    installed shard config does not own the (volume, bucket) slot.

    Unsharded deployments (no config row) pass through untouched, so the
    single-ring path pays one cached `system` get and nothing else.
    """
    cfg = store.get("system", "shard_config")
    if cfg is None:
        return
    slot = slot_for(volume, bucket, cfg["slot_count"])
    if slot not in cfg["owned"]:
        raise OMError(
            SHARD_MOVED,
            f"slot {slot} of {volume}/{bucket} not owned by "
            f"{cfg['shard_id']} at epoch {cfg['epoch']}")
