"""Two-phase cross-shard transactions: rename and bucket link.

The rare ops that span shard rings (a key moving between buckets, a
bucket link whose source lives elsewhere) run a prepare/commit protocol
with the decision journaled on the ROOT ring:

  begin (root) -> prepare on both shard rings -> decide (root)
       -> commit/abort on both shard rings -> end (root)

Every phase record is a replicated ring entry, so a coordinator crash
at ANY point is recoverable: `recover()` re-reads the root journal and
drives open transactions to their decided outcome (or aborts undecided
ones). The shard-side requests are idempotent on replay — a commit or
abort for a transaction whose intent row is gone is a no-op — so
recovery can re-drive a phase that may or may not have landed before
the crash (the classic presumed-abort 2PC shape; Azure Storage ATC '12
runs the same coordinator-journal pattern over its partition map).

All side effects that can FAIL (validation, quota) happen at prepare
time; commit and abort only resolve the staged intent, so a decided
transaction cannot wedge on a business-rule error.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ozone_tpu.om.requests import (
    BUCKET_NOT_FOUND,
    INVALID_REQUEST,
    KEY_NOT_FOUND,
    OMError,
    OMRequest,
    check_and_charge_quota,
    preserve_preimage,
)
from ozone_tpu.om.metadata import bucket_key, key_key
from ozone_tpu.om.sharding.shardmap import SHARD_MOVED, ShardMap, check_shard
from ozone_tpu.utils.metrics import registry

METRICS = registry("om.shard")


def _intent_key(txn_id: str, op: str) -> str:
    # keyed per-op: when BOTH participants of a transaction land on the
    # same ring (same-shard cross-bucket rename) each stages its own row
    return f"txn_intent/{txn_id}/{op}"


def _journal_key(txn_id: str) -> str:
    return f"txn/{txn_id}"


@dataclass
class ShardPrepare(OMRequest):
    """Phase 1 on a participant ring: validate, stage an intent row,
    and take every charge that could fail (quota) — so the later
    commit cannot be refused. `epoch` is the coordinator's shard-map
    epoch: a participant whose replicated shard config has moved past
    it rejects the prepare (SHARD_MOVED) instead of staging state for
    a slot it may no longer own by commit time."""

    txn_id: str
    op: str  # rename_src | rename_dst | link_src | link_dst
    payload: dict
    epoch: int

    def apply(self, store):
        ik = _intent_key(self.txn_id, self.op)
        staged = store.get("system", ik)
        if staged is not None:
            return staged.get("result")  # log replay: already prepared
        cfg = store.get("system", "shard_config")
        if cfg is not None and self.epoch < cfg["epoch"]:
            raise OMError(
                SHARD_MOVED,
                f"prepare fenced: coordinator epoch {self.epoch} < "
                f"shard epoch {cfg['epoch']}")
        vol, bkt = self.payload["volume"], self.payload["bucket"]
        check_shard(store, vol, bkt)
        handler = getattr(self, f"_prepare_{self.op}", None)
        if handler is None:
            raise OMError(INVALID_REQUEST, f"unknown 2pc op {self.op!r}")
        result = handler(store, vol, bkt)
        store.put("system", ik,
                  {"op": self.op, "payload": self.payload,
                   "epoch": self.epoch, "result": result})
        return result

    # -- per-op prepare bodies (each returns the value the coordinator
    #    threads into the sibling prepare) ----------------------------
    def _prepare_rename_src(self, store, vol, bkt):
        src = key_key(vol, bkt, self.payload["key"])
        info = store.get("keys", src)
        if info is None:
            raise OMError(KEY_NOT_FOUND, src)
        preserve_preimage(store, vol, bkt, src)
        store.delete("keys", src)
        check_and_charge_quota(store, vol, bkt,
                               -int(info.get("size", 0)), -1)
        return info

    def _prepare_rename_dst(self, store, vol, bkt):
        bk = bucket_key(vol, bkt)
        brow = store.get("buckets", bk)
        if brow is None:
            raise OMError(BUCKET_NOT_FOUND, bk)
        if brow.get("source"):
            raise OMError(INVALID_REQUEST,
                          f"cannot rename into bucket link {bk}")
        dst = key_key(vol, bkt, self.payload["new_key"])
        if store.get("keys", dst) is not None:
            raise OMError(INVALID_REQUEST,
                          f"rename destination {dst} already exists")
        info = self.payload["info"]
        # growth charge at PREPARE: the only phase allowed to refuse
        check_and_charge_quota(store, vol, bkt,
                               int(info.get("size", 0)), 1)
        return True

    def _prepare_link_src(self, store, vol, bkt):
        bk = bucket_key(vol, bkt)
        brow = store.get("buckets", bk)
        if brow is None:
            raise OMError(BUCKET_NOT_FOUND, bk)
        return {"replication": brow.get("replication", ""),
                "layout": brow.get("layout", "")}

    def _prepare_link_dst(self, store, vol, bkt):
        bk = bucket_key(vol, bkt)
        if store.get("buckets", bk) is not None:
            raise OMError(INVALID_REQUEST,
                          f"bucket {bk} already exists")
        return True


@dataclass
class ShardCommit(OMRequest):
    """Phase 2 (decided COMMIT): resolve the staged intent. Deliberately
    unfenceable by epoch — once the root journal says commit, the shard
    holding the intent must resolve it even if the slot has since moved
    (the intent row, not the slot map, is the authority here); `epoch`
    is recorded for the audit trail."""

    txn_id: str
    epoch: int

    def apply(self, store):
        resolved = []
        prefix = f"txn_intent/{self.txn_id}/"
        for ik, staged in list(store.iterate("system", prefix)):
            op, payload = staged["op"], staged["payload"]
            vol, bkt = payload["volume"], payload["bucket"]
            if op == "rename_dst":
                info = dict(payload["info"])
                info["name"] = payload["new_key"]
                dst = key_key(vol, bkt, payload["new_key"])
                preserve_preimage(store, vol, bkt, dst)
                store.put("keys", dst, info)
            elif op == "link_dst":
                OMRequest.from_json(payload["request"]).apply(store)
            # rename_src / link_src: the prepare already did the work
            store.delete("system", ik)
            resolved.append(op)
        return resolved or None


@dataclass
class ShardAbort(OMRequest):
    """Phase 2 (decided ABORT or undecided at recovery): undo the
    staged intent. Like commit, never refused by epoch — recovery must
    be able to drain an intent wherever it sits."""

    txn_id: str
    epoch: int

    def apply(self, store):
        resolved = []
        prefix = f"txn_intent/{self.txn_id}/"
        for ik, staged in list(store.iterate("system", prefix)):
            op, payload = staged["op"], staged["payload"]
            vol, bkt = payload["volume"], payload["bucket"]
            if op == "rename_src":
                info = staged["result"]
                store.put("keys",
                          key_key(vol, bkt, payload["key"]), info)
                check_and_charge_quota(store, vol, bkt,
                                       int(info.get("size", 0)), 1)
            elif op == "rename_dst":
                info = payload["info"]
                check_and_charge_quota(store, vol, bkt,
                                       -int(info.get("size", 0)), -1)
            # link_src / link_dst: marker only
            store.delete("system", ik)
            resolved.append(op)
        return resolved or None


@dataclass
class TxnJournal(OMRequest):
    """Root-ring coordinator journal entry. Phases: begin ->
    decide-commit | decide-abort -> end (row deleted). The phase
    ordering is monotonic under replay: a stale `begin` cannot
    overwrite a recorded decision."""

    txn_id: str
    phase: str  # begin | decide-commit | decide-abort | end
    record: dict = field(default_factory=dict)

    _ORDER = {"begin": 0, "decide-abort": 1, "decide-commit": 1,
              "end": 2}

    def apply(self, store):
        jk = _journal_key(self.txn_id)
        cur = store.get("system", jk)
        if self.phase == "end":
            store.delete("system", jk)
            return None
        if cur is not None and \
                self._ORDER[cur["phase"]] >= self._ORDER[self.phase]:
            return cur  # replay of an earlier phase: keep the decision
        row = {"txn_id": self.txn_id, "phase": self.phase,
               "record": self.record or (cur or {}).get("record", {})}
        store.put("system", jk, row)
        return row


class CrossShardCoordinator:
    """Drives the 2PC above. Parameterized over submission callables so
    the same coordinator serves the in-process sharded plane and a
    daemon fronting real rings:

      root_submit(request)           -> replicated apply on the root ring
      shard_submit(shard_id, request)-> replicated apply on a shard ring
      root_store                     -> the root ring's local store
                                        (recovery scans the journal)
    """

    def __init__(self, root_submit: Callable[[OMRequest], Any],
                 shard_submit: Callable[[str, OMRequest], Any],
                 root_store,
                 map_fn: Callable[[], ShardMap]):
        self._root_submit = root_submit
        self._shard_submit = shard_submit
        self._root_store = root_store
        self._map_fn = map_fn
        self.metrics = METRICS

    # -- public ops ----------------------------------------------------
    def rename_cross(self, volume: str, src_bucket: str, key: str,
                     dst_bucket: str, new_key: str) -> dict:
        """Move a key between buckets (possibly between shards):
        returns the moved key info."""
        m = self._map_fn()
        s_src = m.shard_for(volume, src_bucket)
        s_dst = m.shard_for(volume, dst_bucket)
        txn_id = uuid.uuid4().hex
        record = {"kind": "rename", "volume": volume,
                  "src_bucket": src_bucket, "key": key,
                  "dst_bucket": dst_bucket, "new_key": new_key,
                  "src_shard": s_src, "dst_shard": s_dst,
                  "epoch": m.epoch}
        self._root_submit(TxnJournal(txn_id, "begin", record))
        try:
            info = self._shard_submit(s_src, ShardPrepare(
                txn_id, "rename_src",
                {"volume": volume, "bucket": src_bucket, "key": key},
                epoch=m.epoch))
            self.metrics.counter("cross_shard_prepares").inc()
            self._shard_submit(s_dst, ShardPrepare(
                txn_id, "rename_dst",
                {"volume": volume, "bucket": dst_bucket,
                 "new_key": new_key, "info": info},
                epoch=m.epoch))
            self.metrics.counter("cross_shard_prepares").inc()
        except Exception:
            self._abort(txn_id, record, m.epoch, (s_src, s_dst))
            raise
        self._root_submit(TxnJournal(txn_id, "decide-commit", record))
        self._commit(txn_id, m.epoch, (s_src, s_dst))
        info = dict(info)
        info["name"] = new_key
        return info

    def link_bucket_cross(self, create_bucket_request) -> None:
        """Create a bucket link whose SOURCE bucket lives on another
        shard: validate the source there, stage the CreateBucket on the
        link's own shard, then commit both."""
        rq = create_bucket_request
        m = self._map_fn()
        s_src = m.shard_for(rq.source_volume, rq.source_bucket)
        s_dst = m.shard_for(rq.volume, rq.bucket)
        txn_id = uuid.uuid4().hex
        record = {"kind": "link", "volume": rq.volume,
                  "bucket": rq.bucket,
                  "source_volume": rq.source_volume,
                  "source_bucket": rq.source_bucket,
                  "src_shard": s_src, "dst_shard": s_dst,
                  "epoch": m.epoch}
        self._root_submit(TxnJournal(txn_id, "begin", record))
        try:
            self._shard_submit(s_src, ShardPrepare(
                txn_id, "link_src",
                {"volume": rq.source_volume,
                 "bucket": rq.source_bucket},
                epoch=m.epoch))
            self.metrics.counter("cross_shard_prepares").inc()
            self._shard_submit(s_dst, ShardPrepare(
                txn_id, "link_dst",
                {"volume": rq.volume, "bucket": rq.bucket,
                 "request": rq.to_json()},
                epoch=m.epoch))
            self.metrics.counter("cross_shard_prepares").inc()
        except Exception:
            self._abort(txn_id, record, m.epoch, (s_src, s_dst))
            raise
        self._root_submit(TxnJournal(txn_id, "decide-commit", record))
        self._commit(txn_id, m.epoch, (s_src, s_dst))

    # -- phase 2 drivers ----------------------------------------------
    def _commit(self, txn_id: str, epoch: int,
                shards: tuple[str, str]) -> None:
        for sid in dict.fromkeys(shards):  # dedupe, keep order
            self._shard_submit(sid, ShardCommit(txn_id, epoch=epoch))
        self.metrics.counter("cross_shard_commits").inc()
        self._root_submit(TxnJournal(txn_id, "end"))

    def _abort(self, txn_id: str, record: dict, epoch: int,
               shards: tuple[str, str]) -> None:
        self._root_submit(TxnJournal(txn_id, "decide-abort", record))
        done = True
        for sid in dict.fromkeys(shards):
            try:
                self._shard_submit(sid, ShardAbort(txn_id, epoch=epoch))
            except Exception:
                # participant unreachable: the decision is journaled;
                # recovery re-drives this abort when the shard returns
                done = False
        self.metrics.counter("cross_shard_aborts").inc()
        if done:
            self._root_submit(TxnJournal(txn_id, "end"))

    # -- crash recovery ------------------------------------------------
    def recover(self) -> list[dict]:
        """Drive every open journal entry to its decided outcome:
        decide-commit -> commit everywhere; begin / decide-abort ->
        abort everywhere (presumed abort for the undecided). Returns
        the resolved records."""
        resolved = []
        for _, row in list(self._root_store.iterate("system", "txn/")):
            txn_id, phase = row["txn_id"], row["phase"]
            rec = row.get("record", {})
            shards = tuple(s for s in (rec.get("src_shard"),
                                       rec.get("dst_shard")) if s)
            epoch = int(rec.get("epoch", 0))
            if phase == "decide-commit":
                self._commit(txn_id, epoch, shards)
            else:
                self._abort(txn_id, rec, epoch, shards)
            resolved.append({"txn_id": txn_id, "phase": phase, **rec})
        return resolved
