"""OM bucket snapshots + snapshot diff.

Capability mirror of the reference's OM snapshots (ozone-manager
OmSnapshotManager.java:110: per-bucket snapshots as RocksDB checkpoints in
a snapshot chain; SnapshotDiffManager computing key diffs via the
compaction-DAG tracker rocksdb-checkpoint-differ RocksDBCheckpointDiffer
.java:102 + native SST reading). Round 5: OBS/LEGACY snapshots are
COPY-ON-WRITE — creation writes only chain metadata (O(#snapshots), the
role the reference's O(1) checkpoint plays; the round-5 scale run
measured the old materialize-at-create at 40 s for a 1M-key bucket),
and each snapshot's overlay accumulates pre-images as mutations touch
live rows (``requests.preserve_preimage``). Value-at-snapshot resolves
to the oldest overlay entry among snapshots >= it, else the live row;
ABSENT markers keep later-created keys out. FSO buckets and
pre-upgrade snapshots stay materialized and read exactly as before.
Snapdiff compares two snapshots (or snapshot vs live) by key: added /
deleted / modified / renamed (delete+add pairs matched by object id,
the SnapshotDiffManager.java:1246 RENAME mechanism), served O(changes)
from the update journal, or from the COW overlay union (which survives
restarts/retention), or by full-listing comparison as the last resort.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ozone_tpu.om.om import OzoneManager
from ozone_tpu.om.requests import OMError, snap_prefix as _snap_prefix

SNAP_TABLE = "keys"  # snapshot rows live in the keys table under a prefix


@dataclass
class SnapshotInfo:
    volume: str
    bucket: str
    name: str
    snap_id: str
    created: float
    previous: Optional[str] = None  # snapshot chain link
    #: round 5: True = copy-on-write snapshot (overlay holds only
    #: pre-images of rows mutated while it was newest); False =
    #: materialized-at-create (pre-upgrade snapshots)
    cow: bool = False
    #: COW over the FSO tables: reads walk the directory tree as-of-
    #: snapshot through SnapshotStoreView instead of path-keyed rows
    fso: bool = False

    def to_json(self) -> dict:
        return self.__dict__.copy()


class SnapshotStoreView:
    """Read-only store facade serving the FSO tables (dirs / files /
    dir_ids) AS OF a COW snapshot: each get resolves to the oldest
    overlay entry among the chain ``snaps`` (this snapshot to newest,
    oldest first), else the live row — the same first-write-wins
    algebra the OBS path uses, applied per table with ``#table#key``
    overlay rows. All other tables pass through to the live store, so
    fso.py's read machinery (resolve, get_status, list_status,
    walk_files_paged) runs unchanged against a point-in-time tree —
    including paths as they were BEFORE later directory renames, which
    the old materialize-at-create design could only freeze."""

    _COW_TABLES = ("dirs", "files", "dir_ids")

    def __init__(self, store, volume: str, bucket: str,
                 snaps: list[dict]):
        self._store = store
        self._volume = volume
        self._bucket = bucket
        self._snaps = snaps

    def _okey(self, snap_id: str, table: str, key: str) -> str:
        return (f"{_snap_prefix(self._volume, self._bucket, snap_id)}"
                f"/#{table}#{key}")

    def get(self, table: str, key: str):
        if table not in self._COW_TABLES:
            return self._store.get(table, key)
        from ozone_tpu.om.requests import is_absent_marker

        for s in self._snaps:
            v = self._store.get("keys",
                                self._okey(s["snap_id"], table, key))
            if v is not None:
                return None if is_absent_marker(v) else v
        return self._store.get(table, key)

    def exists(self, table: str, key: str) -> bool:
        return self.get(table, key) is not None

    def iterate_range(self, table: str, prefix: str = "",
                      start_after: str = "", limit=None):
        if table not in self._COW_TABLES:
            return self._store.iterate_range(table, prefix, start_after,
                                             limit)
        from ozone_tpu.om.requests import is_absent_marker

        merged: dict[str, dict] = {}
        floor = start_after or ""
        for s in self._snaps:
            op = self._okey(s["snap_id"], table, prefix)
            head = len(self._okey(s["snap_id"], table, ""))
            for k, v in self._store.iterate("keys", op):
                if k[head:] > floor:
                    merged.setdefault(k[head:], v)
        # overlays are O(changes); the LIVE scan is the one that must
        # stay windowed for walk_files_paged's paging to hold. Overlay
        # entries can both HIDE live rows (absent markers) and ADD rows
        # the window didn't count, so over-fetch by the overlay size.
        live_limit = None if limit is None else limit + len(merged)
        for k, v in self._store.iterate_range(table, prefix,
                                              start_after=floor,
                                              limit=live_limit):
            merged.setdefault(k, v)
        out = [(k, merged[k]) for k in sorted(merged)
               if not is_absent_marker(merged[k])]
        return out[:limit] if limit is not None else out

    def iterate(self, table: str, prefix: str = ""):
        yield from self.iterate_range(table, prefix)


class SnapshotManager:
    def __init__(self, om: OzoneManager):
        self.om = om

    # ------------------------------------------------------------- create
    def create_snapshot(self, volume: str, bucket: str, name: str) -> SnapshotInfo:
        """Materialize via the replicated request log (CreateSnapshot
        request), so HA replicas hold identical snapshot state."""
        from ozone_tpu.om import requests as rq

        out = self.om.submit(rq.CreateSnapshot(volume, bucket, name))
        return SnapshotInfo(**out)

    def list_snapshots(self, volume: str, bucket: str) -> list[SnapshotInfo]:
        out = []
        for _, v in self.om.store.iterate(
            "open_keys", f"/.snapmeta/{volume}/{bucket}/"
        ):
            out.append(SnapshotInfo(**v))
        return sorted(out, key=lambda s: s.created)

    def get_snapshot(self, volume: str, bucket: str, name: str) -> SnapshotInfo:
        from ozone_tpu.om.requests import snapmeta_key

        v = self.om.store.get("open_keys",
                              snapmeta_key(volume, bucket, name))
        if v is None:
            raise OMError("SNAPSHOT_NOT_FOUND", name)
        return SnapshotInfo(**v)

    def delete_snapshot(self, volume: str, bucket: str, name: str) -> None:
        from ozone_tpu.om import requests as rq

        self.om.submit(rq.DeleteSnapshot(volume, bucket, name))

    # ------------------------------------------------------------- reads
    def _chain_from(self, volume: str, bucket: str,
                    snap_id: str) -> list[dict]:
        """Snapshots from `snap_id` (inclusive) to newest, oldest
        first — the COW read walk's scope."""
        from ozone_tpu.om.requests import bucket_snapshots

        snaps = bucket_snapshots(self.om.store, volume, bucket)
        idx = next(i for i, s in enumerate(snaps)
                   if s["snap_id"] == snap_id)
        return snaps[idx:]

    def _value_at(self, volume: str, bucket: str, info: "SnapshotInfo",
                  key: str) -> Optional[dict]:
        """The key's row as of snapshot `info` (None = did not exist).

        Materialized snapshots are self-contained: their own overlay IS
        the row set. COW snapshots resolve via the oldest overlay entry
        among snapshots >= info — sound because a snapshot with no
        entry for the key proves the key was not mutated during its
        reign — falling through to the live table (COW snapshots are
        always newer than every materialized one in a chain, so the
        walk never crosses modes)."""
        from ozone_tpu.om.requests import is_absent_marker

        store = self.om.store
        if not info.cow:
            return store.get(
                "keys",
                f"{_snap_prefix(volume, bucket, info.snap_id)}/{key}")
        for s in self._chain_from(volume, bucket, info.snap_id):
            v = store.get(
                "keys",
                f"{_snap_prefix(volume, bucket, s['snap_id'])}/{key}")
            if v is not None:
                return None if is_absent_marker(v) else v
        return store.get("keys", f"/{volume}/{bucket}/{key}")

    def _fso_view(self, volume: str, bucket: str,
                  info: "SnapshotInfo") -> SnapshotStoreView:
        return SnapshotStoreView(
            self.om.store, volume, bucket,
            self._chain_from(volume, bucket, info.snap_id))

    @staticmethod
    def _fso_row(entry: dict) -> dict:
        """walk/list entries -> the snapshot row shape (path-named,
        tree metadata stripped) the materialized design stored."""
        return {k: v for k, v in entry.items() if k not in ("type",
                                                            "path")}

    def list_keys(self, volume: str, bucket: str, name: str) -> list[dict]:
        from ozone_tpu.om.requests import is_absent_marker

        info = self.get_snapshot(volume, bucket, name)
        store = self.om.store
        if info.cow and info.fso:
            from ozone_tpu.om import fso

            view = self._fso_view(volume, bucket, info)
            return [self._fso_row(e)
                    for e in fso.walk_files_paged(view, volume, bucket)]
        if not info.cow:
            prefix = _snap_prefix(volume, bucket, info.snap_id) + "/"
            return [v for _, v in store.iterate("keys", prefix)]
        # COW merge: oldest overlay >= this snapshot wins, live fills
        # the never-mutated remainder
        merged: dict[str, dict] = {}
        for s in self._chain_from(volume, bucket, info.snap_id):
            p = _snap_prefix(volume, bucket, s["snap_id"]) + "/"
            for k, v in store.iterate("keys", p):
                merged.setdefault(k[len(p):], v)
        base = f"/{volume}/{bucket}/"
        for k, v in store.iterate("keys", base):
            if not k.startswith("/.snap"):
                merged.setdefault(k[len(base):], v)
        return [merged[k] for k in sorted(merged)
                if not is_absent_marker(merged[k])]

    def lookup_key(self, volume: str, bucket: str, name: str, key: str) -> dict:
        info = self.get_snapshot(volume, bucket, name)
        if info.cow and info.fso:
            from ozone_tpu.om import fso

            view = self._fso_view(volume, bucket, info)
            try:
                st = fso.lookup_file(view, volume, bucket, key)
            except OMError:
                raise OMError("KEY_NOT_FOUND", f"{key}@snapshot:{name}")
            return self._fso_row(st)
        v = self._value_at(volume, bucket, info, key)
        if v is None:
            raise OMError("KEY_NOT_FOUND", f"{key}@snapshot:{name}")
        return v

    # ------------------------------------------------------------- diff
    @staticmethod
    def _key_sig(v: dict) -> tuple:
        return (v["size"], v.get("modified"), v.get("block_groups"))

    @staticmethod
    def _pair_renames(deleted: dict, added: dict
                      ) -> tuple[list, list, list]:
        """Pair deleted+added rows whose object_id matches into RENAME
        entries (the object-ID tracking SnapshotDiffManager.java:1246
        uses): returns (added_names, deleted_names, renamed_pairs).
        Rows predating object ids (or genuinely new objects) stay plain
        adds/deletes."""
        by_id = {
            v.get("object_id"): n
            for n, v in deleted.items() if v.get("object_id")
        }
        renamed, still_added = [], []
        gone = set(deleted)
        for n in sorted(added):
            src = by_id.get(added[n].get("object_id"))
            if src is not None and src in gone:
                renamed.append([src, n])
                gone.discard(src)
            else:
                still_added.append(n)
        return still_added, sorted(gone), sorted(renamed)

    def _incremental_diff(self, volume: str, bucket: str,
                          old_info: SnapshotInfo,
                          new_info: Optional[SnapshotInfo]) -> Optional[dict]:
        """O(changes) diff from the store's update journal (the role the
        compaction-DAG SST tracking plays in the reference's
        RocksDBCheckpointDiffer.getSSTDiffList:860): snapshot markers
        pin journal positions, and only keys TOUCHED between the two
        positions are compared. Returns None when the journal no longer
        reaches back (restart, HA install, retention) or for FSO
        buckets, whose journal rows key files by parent id — a deleted
        row's path is not recoverable there, so FSO takes the
        full-listing fallback."""
        store = self.om.store
        binfo = self.om.bucket_info(volume, bucket)
        if binfo.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            return None
        from_mark = store.snapshot_markers.get(old_info.snap_id)
        to_mark = (store.snapshot_markers.get(new_info.snap_id)
                   if new_info is not None else store.txid)
        if from_mark is None or to_mark is None or to_mark < from_mark:
            return None
        updates, _, complete = store.get_updates_since(from_mark)
        if not complete:
            return None
        base = f"/{volume}/{bucket}/"
        names: set[str] = set()
        for txid, table, key, _v in updates:
            if txid > to_mark:
                break
            if table == "keys" and key.startswith(base):
                names.add(key[len(base):])
        added_v, deleted_v, modified = {}, {}, []
        for name in sorted(names):
            ov = self._value_at(volume, bucket, old_info, name)
            nv = (self._value_at(volume, bucket, new_info, name)
                  if new_info is not None
                  else store.get("keys", base + name))
            if ov is None and nv is not None:
                added_v[name] = nv
            elif ov is not None and nv is None:
                deleted_v[name] = ov
            elif ov is not None and nv is not None \
                    and self._key_sig(ov) != self._key_sig(nv):
                modified.append(name)
            # both None: created AND deleted inside the window
        added, deleted, renamed = self._pair_renames(deleted_v, added_v)
        return {"added": added, "deleted": deleted, "modified": modified,
                "renamed": renamed,
                "mode": "incremental", "keys_examined": len(names)}

    def _overlay_diff(self, volume: str, bucket: str,
                      old_info: SnapshotInfo,
                      new_info: Optional[SnapshotInfo]) -> Optional[dict]:
        """COW-native diff: the keys mutated between two snapshots are
        EXACTLY the union of the overlay key sets of [old, new) — each
        overlay entry is the pre-image of a first-mutation during that
        snapshot's reign. O(changes) even when the journal no longer
        reaches back (the incremental path's restart/retention gap).
        Requires `old` (and everything after it) to be COW."""
        if not old_info.cow or old_info.fso:
            # FSO overlays are id-keyed; the full-listing comparison
            # (over tree-at-snapshot listings) derives their paths
            return None
        if new_info is not None and new_info.created < old_info.created:
            return None  # reversed pair: the full comparison handles it
        store = self.om.store
        names: set[str] = set()
        for s in self._chain_from(volume, bucket, old_info.snap_id):
            if new_info is not None and s["snap_id"] == new_info.snap_id:
                break
            p = _snap_prefix(volume, bucket, s["snap_id"]) + "/"
            for k, _v in store.iterate("keys", p):
                names.add(k[len(p):])
        base = f"/{volume}/{bucket}/"
        added_v, deleted_v, modified = {}, {}, []
        for name in sorted(names):
            ov = self._value_at(volume, bucket, old_info, name)
            nv = (self._value_at(volume, bucket, new_info, name)
                  if new_info is not None
                  else store.get("keys", base + name))
            if ov is None and nv is not None:
                added_v[name] = nv
            elif ov is not None and nv is None:
                deleted_v[name] = ov
            elif ov is not None and nv is not None \
                    and self._key_sig(ov) != self._key_sig(nv):
                modified.append(name)
        added, deleted, renamed = self._pair_renames(deleted_v, added_v)
        return {"added": added, "deleted": deleted, "modified": modified,
                "renamed": renamed,
                "mode": "overlay", "keys_examined": len(names)}

    def snapshot_diff(self, volume: str, bucket: str,
                      from_snapshot: str,
                      to_snapshot: Optional[str] = None) -> dict:
        """Key diff between two snapshots (or a snapshot and live state).

        Returns {added, deleted, modified} key-name lists
        (SnapshotDiffManager's SnapshotDiffReport analog). Served
        incrementally from the update journal when the snapshot's
        journal marker is still reachable — O(changes), not
        O(namespace); full-listing comparison otherwise."""
        old_info = self.get_snapshot(volume, bucket, from_snapshot)
        new_info = (self.get_snapshot(volume, bucket, to_snapshot)
                    if to_snapshot is not None else None)
        out = self._incremental_diff(volume, bucket, old_info, new_info)
        if out is not None:
            return out
        out = self._overlay_diff(volume, bucket, old_info, new_info)
        if out is not None:
            return out
        old = {
            k["name"]: k
            for k in self.list_keys(volume, bucket, from_snapshot)
        }
        if to_snapshot is None:
            new = {
                k["name"]: k
                for k in self.om.list_keys(volume, bucket)
                if not k["name"].startswith(".snap")
            }
        else:
            new = {
                k["name"]: k
                for k in self.list_keys(volume, bucket, to_snapshot)
            }
        modified = sorted(
            n
            for n in set(old) & set(new)
            if self._key_sig(old[n]) != self._key_sig(new[n])
        )
        added, deleted, renamed = self._pair_renames(
            {n: old[n] for n in set(old) - set(new)},
            {n: new[n] for n in set(new) - set(old)},
        )
        return {"added": added, "deleted": deleted, "modified": modified,
                "renamed": renamed, "mode": "full"}


class SnapshotDiffJobs:
    """Job-based paged snapshot diff (the SnapshotDiffManager.java:98
    model: diffs run as jobs — submit returns IN_PROGRESS, polling the
    same pair returns the job's status, and a DONE job serves its report
    in pages via an opaque continuation token). Jobs are per-OM-process
    state, like the reference where diff jobs live beside the leader's
    local RocksDB; entries are flat DiffReportEntry analogs
    {op: ADD|DELETE|MODIFY|RENAME, key[, target]} in deterministic
    order (renames, deletes, modifies, adds)."""

    #: completed jobs kept before oldest-first eviction (reference:
    #: snapDiffJobTable with a cleanup service)
    MAX_JOBS = 64

    def __init__(self, om: OzoneManager):
        self.om = om
        import threading

        self._lock = threading.Lock()
        self._by_key: dict[tuple, dict] = {}
        self._by_name: dict[tuple, dict] = {}
        self._by_id: dict[str, dict] = {}

    def submit(self, volume: str, bucket: str, from_snapshot: str,
               to_snapshot: Optional[str] = None) -> dict:
        import threading
        import time
        import uuid

        mgr = self.om._snapshots()
        name_key = (volume, bucket, from_snapshot, to_snapshot or "")
        try:
            # jobs key on snapshot IDs, not names — a deleted-and-
            # recreated snapshot of the same name is a different diff
            from_id = mgr.get_snapshot(volume, bucket,
                                       from_snapshot).snap_id
            # a diff against live state is only valid for the store
            # state it ran at: key it by the current txid so later
            # submits after writes compute a fresh report
            to_id = (mgr.get_snapshot(volume, bucket,
                                      to_snapshot).snap_id
                     if to_snapshot is not None
                     else f"live@{self.om.store.txid}")
        except OMError:
            # a named snapshot is gone — a finished job's report is
            # already materialized, so keep serving its status rather
            # than erroring a poll that raced a snapshot delete
            with self._lock:
                job = self._by_name.get(name_key)
            if job is not None:
                return self._view(job)
            raise
        key = (volume, bucket, from_id, to_id)
        user, groups = self.om.current_user()
        with self._lock:
            job = self._by_key.get(key)
            if job is not None and job["status"] == "FAILED":
                job = None  # transient failures retry on resubmission
            if job is None:
                job = {
                    "job_id": uuid.uuid4().hex[:16],
                    "status": "IN_PROGRESS",
                    "volume": volume,
                    "bucket": bucket,
                    "from_snapshot": from_snapshot,
                    "to_snapshot": to_snapshot,
                    "created": time.time(),
                    "error": "",
                    "total": 0,
                    "mode": "",
                    "entries": [],
                }
                self._by_key[key] = job
                self._by_name[name_key] = job
                self._by_id[job["job_id"]] = job
                self._evict_locked()
                threading.Thread(
                    target=self._run,
                    args=(job, volume, bucket, from_snapshot,
                          to_snapshot, user, groups),
                    name=f"snapdiff-{job['job_id']}", daemon=True,
                ).start()
        return self._view(job)

    def _evict_locked(self) -> None:
        """Oldest-first eviction of finished jobs so the maps stay
        bounded (entry lists can be large)."""
        while len(self._by_id) > self.MAX_JOBS:
            victims = sorted(
                (j for j in self._by_id.values()
                 if j["status"] != "IN_PROGRESS"),
                key=lambda j: j["created"])
            if not victims:
                return
            v = victims[0]
            self._by_id.pop(v["job_id"], None)
            for m in (self._by_key, self._by_name):
                for k in [k for k, j in m.items()
                          if j["job_id"] == v["job_id"]]:
                    del m[k]

    @staticmethod
    def _view(job: dict) -> dict:
        return {k: job[k] for k in (
            "job_id", "status", "volume", "bucket", "from_snapshot",
            "to_snapshot", "created", "error", "total", "mode")}

    def _run(self, job: dict, volume: str, bucket: str,
             from_snapshot: str, to_snapshot: Optional[str],
             user=None, groups=()) -> None:
        try:
            # re-bind the submitter's identity: this worker thread has
            # no thread-local context, and an unbound thread would run
            # ACL checks as the trusted superuser
            with self.om.user_context(user, groups):
                out = self.om._snapshots().snapshot_diff(
                    volume, bucket, from_snapshot, to_snapshot)
            entries: list[dict] = []
            for src, dst in out.get("renamed", []):
                entries.append({"op": "RENAME", "key": src, "target": dst})
            for n in out.get("deleted", []):
                entries.append({"op": "DELETE", "key": n})
            for n in out.get("modified", []):
                entries.append({"op": "MODIFY", "key": n})
            for n in out.get("added", []):
                entries.append({"op": "ADD", "key": n})
            job["entries"] = entries
            job["total"] = len(entries)
            job["mode"] = out.get("mode", "")
            job["status"] = "DONE"
        except Exception as e:  # noqa: BLE001 - job surface, not a crash
            job["error"] = str(e)
            job["status"] = "FAILED"

    def page(self, job_id: str, token: str = "",
             page_size: int = 1000) -> dict:
        from ozone_tpu.om.requests import INVALID_REQUEST

        job = self._by_id.get(job_id)
        if job is None:
            raise OMError(INVALID_REQUEST, f"no snapshot-diff job {job_id}")
        view = self._view(job)
        if job["status"] != "DONE":
            return {**view, "entries": [], "next_token": ""}
        try:
            off = int(token) if token else 0
        except ValueError:
            raise OMError(INVALID_REQUEST, f"bad page token {token!r}")
        if off < 0:
            raise OMError(INVALID_REQUEST, f"bad page token {token!r}")
        try:
            size = max(1, int(page_size))
        except (TypeError, ValueError):
            raise OMError(INVALID_REQUEST,
                          f"bad page size {page_size!r}")
        entries = job["entries"][off:off + size]
        nxt = str(off + size) if off + size < job["total"] else ""
        return {**view, "entries": entries, "next_token": nxt}
