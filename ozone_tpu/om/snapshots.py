"""OM bucket snapshots + snapshot diff.

Capability mirror of the reference's OM snapshots (ozone-manager
OmSnapshotManager.java:110: per-bucket snapshots as RocksDB checkpoints in
a snapshot chain; SnapshotDiffManager computing key diffs via the
compaction-DAG tracker rocksdb-checkpoint-differ RocksDBCheckpointDiffer
.java:102 + native SST reading): here a snapshot materializes the bucket's
key-table rows into a dedicated snapshot table (the sqlite analog of a
checkpoint), snapshots chain per bucket, reads can be served from a
snapshot, and snapdiff compares two snapshots (or snapshot vs live) by
key: added / deleted / modified / renamed (delete+add pairs matched by
object id, the SnapshotDiffManager.java:1246 RENAME mechanism).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ozone_tpu.om.om import OzoneManager
from ozone_tpu.om.requests import OMError, snap_prefix as _snap_prefix

SNAP_TABLE = "keys"  # snapshot rows live in the keys table under a prefix


@dataclass
class SnapshotInfo:
    volume: str
    bucket: str
    name: str
    snap_id: str
    created: float
    previous: Optional[str] = None  # snapshot chain link

    def to_json(self) -> dict:
        return self.__dict__.copy()


class SnapshotManager:
    def __init__(self, om: OzoneManager):
        self.om = om

    # ------------------------------------------------------------- create
    def create_snapshot(self, volume: str, bucket: str, name: str) -> SnapshotInfo:
        """Materialize via the replicated request log (CreateSnapshot
        request), so HA replicas hold identical snapshot state."""
        from ozone_tpu.om import requests as rq

        out = self.om.submit(rq.CreateSnapshot(volume, bucket, name))
        return SnapshotInfo(**out)

    def list_snapshots(self, volume: str, bucket: str) -> list[SnapshotInfo]:
        out = []
        for _, v in self.om.store.iterate(
            "open_keys", f"/.snapmeta/{volume}/{bucket}/"
        ):
            out.append(SnapshotInfo(**v))
        return sorted(out, key=lambda s: s.created)

    def get_snapshot(self, volume: str, bucket: str, name: str) -> SnapshotInfo:
        from ozone_tpu.om.requests import snapmeta_key

        v = self.om.store.get("open_keys",
                              snapmeta_key(volume, bucket, name))
        if v is None:
            raise OMError("SNAPSHOT_NOT_FOUND", name)
        return SnapshotInfo(**v)

    def delete_snapshot(self, volume: str, bucket: str, name: str) -> None:
        from ozone_tpu.om import requests as rq

        self.om.submit(rq.DeleteSnapshot(volume, bucket, name))

    # ------------------------------------------------------------- reads
    def list_keys(self, volume: str, bucket: str, name: str) -> list[dict]:
        info = self.get_snapshot(volume, bucket, name)
        prefix = _snap_prefix(volume, bucket, info.snap_id) + "/"
        return [v for _, v in self.om.store.iterate("keys", prefix)]

    def lookup_key(self, volume: str, bucket: str, name: str, key: str) -> dict:
        info = self.get_snapshot(volume, bucket, name)
        prefix = _snap_prefix(volume, bucket, info.snap_id)
        v = self.om.store.get("keys", f"{prefix}/{key}")
        if v is None:
            raise OMError("KEY_NOT_FOUND", f"{key}@snapshot:{name}")
        return v

    # ------------------------------------------------------------- diff
    @staticmethod
    def _key_sig(v: dict) -> tuple:
        return (v["size"], v.get("modified"), v.get("block_groups"))

    @staticmethod
    def _pair_renames(deleted: dict, added: dict
                      ) -> tuple[list, list, list]:
        """Pair deleted+added rows whose object_id matches into RENAME
        entries (the object-ID tracking SnapshotDiffManager.java:1246
        uses): returns (added_names, deleted_names, renamed_pairs).
        Rows predating object ids (or genuinely new objects) stay plain
        adds/deletes."""
        by_id = {
            v.get("object_id"): n
            for n, v in deleted.items() if v.get("object_id")
        }
        renamed, still_added = [], []
        gone = set(deleted)
        for n in sorted(added):
            src = by_id.get(added[n].get("object_id"))
            if src is not None and src in gone:
                renamed.append([src, n])
                gone.discard(src)
            else:
                still_added.append(n)
        return still_added, sorted(gone), sorted(renamed)

    def _incremental_diff(self, volume: str, bucket: str,
                          old_info: SnapshotInfo,
                          new_info: Optional[SnapshotInfo]) -> Optional[dict]:
        """O(changes) diff from the store's update journal (the role the
        compaction-DAG SST tracking plays in the reference's
        RocksDBCheckpointDiffer.getSSTDiffList:860): snapshot markers
        pin journal positions, and only keys TOUCHED between the two
        positions are compared. Returns None when the journal no longer
        reaches back (restart, HA install, retention) or for FSO
        buckets, whose journal rows key files by parent id — a deleted
        row's path is not recoverable there, so FSO takes the
        full-listing fallback."""
        store = self.om.store
        binfo = self.om.bucket_info(volume, bucket)
        if binfo.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            return None
        from_mark = store.snapshot_markers.get(old_info.snap_id)
        to_mark = (store.snapshot_markers.get(new_info.snap_id)
                   if new_info is not None else store.txid)
        if from_mark is None or to_mark is None or to_mark < from_mark:
            return None
        updates, _, complete = store.get_updates_since(from_mark)
        if not complete:
            return None
        base = f"/{volume}/{bucket}/"
        names: set[str] = set()
        for txid, table, key, _v in updates:
            if txid > to_mark:
                break
            if table == "keys" and key.startswith(base):
                names.add(key[len(base):])
        old_prefix = _snap_prefix(volume, bucket, old_info.snap_id)
        new_prefix = (_snap_prefix(volume, bucket, new_info.snap_id)
                      if new_info is not None else None)
        added_v, deleted_v, modified = {}, {}, []
        for name in sorted(names):
            ov = store.get("keys", f"{old_prefix}/{name}")
            nv = store.get(
                "keys",
                f"{new_prefix}/{name}" if new_prefix else base + name)
            if ov is None and nv is not None:
                added_v[name] = nv
            elif ov is not None and nv is None:
                deleted_v[name] = ov
            elif ov is not None and nv is not None \
                    and self._key_sig(ov) != self._key_sig(nv):
                modified.append(name)
            # both None: created AND deleted inside the window
        added, deleted, renamed = self._pair_renames(deleted_v, added_v)
        return {"added": added, "deleted": deleted, "modified": modified,
                "renamed": renamed,
                "mode": "incremental", "keys_examined": len(names)}

    def snapshot_diff(self, volume: str, bucket: str,
                      from_snapshot: str,
                      to_snapshot: Optional[str] = None) -> dict:
        """Key diff between two snapshots (or a snapshot and live state).

        Returns {added, deleted, modified} key-name lists
        (SnapshotDiffManager's SnapshotDiffReport analog). Served
        incrementally from the update journal when the snapshot's
        journal marker is still reachable — O(changes), not
        O(namespace); full-listing comparison otherwise."""
        old_info = self.get_snapshot(volume, bucket, from_snapshot)
        new_info = (self.get_snapshot(volume, bucket, to_snapshot)
                    if to_snapshot is not None else None)
        out = self._incremental_diff(volume, bucket, old_info, new_info)
        if out is not None:
            return out
        old = {
            k["name"]: k
            for k in self.list_keys(volume, bucket, from_snapshot)
        }
        if to_snapshot is None:
            new = {
                k["name"]: k
                for k in self.om.list_keys(volume, bucket)
                if not k["name"].startswith(".snap")
            }
        else:
            new = {
                k["name"]: k
                for k in self.list_keys(volume, bucket, to_snapshot)
            }
        modified = sorted(
            n
            for n in set(old) & set(new)
            if self._key_sig(old[n]) != self._key_sig(new[n])
        )
        added, deleted, renamed = self._pair_renames(
            {n: old[n] for n in set(old) - set(new)},
            {n: new[n] for n in set(new) - set(old)},
        )
        return {"added": added, "deleted": deleted, "modified": modified,
                "renamed": renamed, "mode": "full"}
