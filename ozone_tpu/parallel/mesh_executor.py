"""Persistent mesh executor: the multi-chip datapath, kept fed.

MULTICHIP_r05 proved the sharded codec correct (bit-exact DP encode,
cross-process psum) but moved ~0.2 MiB/s/device, because every mesh call
re-staged its batch, re-dispatched synchronously, and blocked for the
result. This module gives the mesh the same treatment
`DeviceBatchPipeline` + `CodecService` gave the single chip:

- **Long-lived compiled SPMD programs**, one per (FusedSpec, erasure
  pattern, batch width), resolved once per lane through
  `parallel/sharded.py`'s plan caches — erasure-pattern churn swaps a
  tiny replicated matrix, never the compiled program.
- **Reused host staging buffers**: every dispatch packs into a pooled
  buffer of the lane's constant shape instead of allocating; the pool
  holds depth+1 buffers per shape, the steady-state working set of the
  in-flight window.
- **Depth-N in-flight batches** (``OZONE_TPU_MESH_DEPTH``, default 2):
  dispatch N+1 launches while batches N..N-depth+1 are still on the
  devices; results harvest without blocking the submission path.
- **A submission-queue front end mirroring `codec/service.py` lanes**:
  concurrent operations submit stripes keyed by the same semantic keys
  (`encode_key` / `decode_key`); the dispatcher coalesces them into
  full-width mesh dispatches (per-device batch x mesh size), so a
  reconstruction storm over many containers becomes a few wide
  dispatches instead of per-container dribbles.

Backend policy mirrors `codec/fused.py`: on CPU-only hosts (where XLA's
GF(2) bit-matmul runs orders of magnitude slower than the AVX2 nibble
coder) a lane's program resolves to the **native host twin sharded
across one worker thread per mesh device** — same contract, same
coalescing, and trivially zero XLA compiles — while accelerator meshes
run the jitted SPMD programs. `stats()["mode_*"]` reports which.

Spill: when ``OZONE_TPU_MESH_SPILL=1`` (off by default) the shared
codec service redirects whole overflowing lanes here once its queue
depth crosses ``OZONE_TPU_MESH_SPILL_WATERMARK`` — see
`codec/service.py:_collect_spill_locked`.
"""

from __future__ import annotations

import logging
import math
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

import numpy as np

from ozone_tpu.codec.pipeline import _start_d2h
from ozone_tpu.parallel import sharded
from ozone_tpu.utils.config import env_float, env_int
from ozone_tpu.utils.metrics import MetricsRegistry, registry
from ozone_tpu.utils.tracing import Tracer

log = logging.getLogger(__name__)

#: every mesh-executor signal in ONE registry (prometheus: mesh_*)
METRICS: MetricsRegistry = registry("mesh")

#: in-flight mesh batches per lane family (double buffering = 2; triple
#: buffering = 3 hides longer D2H tails at the cost of one more staged
#: batch of memory per shape)
DEFAULT_DEPTH = 2
#: a single mesh dispatch never packs more stripe slots than this, no
#: matter the mesh size — bounds staged-buffer memory ([256, k, cell])
MAX_DISPATCH_WIDTH = 256
#: added-latency bound for a partial mesh batch waiting for co-batching
#: (the codec service's linger, applied to the mesh front end)
DEFAULT_LINGER_MS = 2.0


def mesh_depth() -> int:
    """The in-flight depth knob (OZONE_TPU_MESH_DEPTH, min 1)."""
    return max(1, env_int("OZONE_TPU_MESH_DEPTH", DEFAULT_DEPTH))


def enabled() -> bool:
    """The executor disable switch (OZONE_TPU_MESH=0)."""
    return os.environ.get("OZONE_TPU_MESH", "1") != "0"


def spill_enabled() -> bool:
    """Codec-service overflow spill onto the mesh
    (OZONE_TPU_MESH_SPILL=1; OFF by default — spilling helps only when
    neighbor chips are otherwise idle, and moves interactive work onto
    a path tuned for throughput, not latency)."""
    return os.environ.get("OZONE_TPU_MESH_SPILL", "0") in (
        "1", "true", "yes", "on")


def spill_watermark() -> int:
    """Queue-depth (stripes) past which the codec service starts
    redirecting whole lanes to the mesh (OZONE_TPU_MESH_SPILL_WATERMARK)."""
    return max(1, env_int("OZONE_TPU_MESH_SPILL_WATERMARK", 64))


def _ambient_deadline():
    from ozone_tpu.client import resilience

    return resilience.current()


class _MeshProgram:
    """One resolved, long-lived mesh program for a semantic key.

    `fn(batch [W, ...]) -> tuple of outputs` where W is any multiple of
    the mesh size up to the dispatch width; `jitted` lists the
    underlying compiled callables for the zero-new-compile probe
    (empty on the host-twin path, which has nothing to compile).
    """

    __slots__ = ("fn", "jitted", "host_twin")

    def __init__(self, fn: Callable, jitted: tuple, host_twin: bool):
        self.fn = fn
        self.jitted = jitted
        self.host_twin = host_twin

    def compile_count(self) -> int:
        """Compiled-executable census across this program's jitted
        callables; steady-state dispatches must not move it."""
        total = 0
        for f in self.jitted:
            try:
                total += int(f._cache_size())
            except Exception:  # ozlint: allow[error-swallowing] -- _cache_size is a private jax probe; absent on some versions, the census just under-counts
                continue
        return total


class _Sub:
    """One submission: `n` same-shape stripes from one operation."""

    __slots__ = ("stripes", "n", "future", "cls", "deadline", "t_enq",
                 "t_enq_wall", "trace_ctx", "tail", "taken",
                 "pending_parts", "parts")

    def __init__(self, stripes: np.ndarray, future: Future, cls: str,
                 deadline, tail: bool):
        self.stripes = stripes
        self.n = int(stripes.shape[0])
        self.future = future
        self.cls = cls
        self.deadline = deadline
        self.t_enq = time.monotonic()
        self.t_enq_wall = time.time()
        self.trace_ctx = Tracer.instance().inject()
        self.tail = tail
        self.taken = 0
        self.pending_parts = 0
        self.parts: list[tuple] = []

    def deadline_t(self) -> float:
        return self.deadline.t_end if self.deadline is not None else math.inf


class _Lane:
    """One coalescing lane: same semantic key, same per-device batch
    width, same QoS class. FIFO of submissions with undispatched
    stripes; the bound program persists for the executor's lifetime
    (unlike the codec service's ephemeral fn bindings, mesh programs
    are the executor's to own — that persistence IS the point)."""

    __slots__ = ("lane_key", "program", "width", "cls", "subs", "queued",
                 "min_deadline_t")

    def __init__(self, lane_key: tuple, program: _MeshProgram,
                 width: int, cls: str):
        self.lane_key = lane_key
        self.program = program
        self.width = max(1, int(width))
        self.cls = cls
        self.subs: deque[_Sub] = deque()
        self.queued = 0
        self.min_deadline_t = math.inf


class MeshExecutor:
    """Per-process owner of the multi-chip datapath.

    `submit(key, stripes, width=...)` enqueues stripe work under a
    codec-service semantic key and returns a Future of the host output
    tuple for exactly those stripes. Submissions sharing (key, width,
    qos) coalesce into full-width mesh dispatches; up to
    ``mesh_depth()`` dispatches stay in flight.
    """

    def __init__(self, mesh=None, depth: Optional[int] = None,
                 axis: str = "dn"):
        if mesh is None:
            mesh = sharded.default_codec_mesh(axis=axis)
        if mesh is None:
            raise ValueError(
                "mesh executor needs a multi-device mesh "
                "(jax.device_count() > 1)")
        self.mesh = mesh
        self.axis = axis
        self.n_devices = int(mesh.devices.size)
        self.depth = depth if depth is not None else mesh_depth()
        self.linger_s = env_float("OZONE_TPU_MESH_LINGER_MS",
                                  DEFAULT_LINGER_MS) / 1000.0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._lanes: dict[tuple, _Lane] = {}
        self._programs: dict[tuple, Optional[_MeshProgram]] = {}
        self._inflight: deque[tuple] = deque()
        #: host staging buffers: (shape, dtype str) -> free list; the
        #: in-flight window recycles depth+1 buffers per lane shape
        self._staging: dict[tuple, list[np.ndarray]] = {}
        self._max_inflight = 0
        #: one worker per mesh device for the host-twin programs (the
        #: production mirror of fused._prefer_host_coder: on CPU-only
        #: hosts the native AVX2 coder outruns XLA's bit-matmul by
        #: orders of magnitude, and the "mesh" is the core count)
        self._workers = ThreadPoolExecutor(
            max_workers=self.n_devices, thread_name_prefix="mesh-dev")
        self._dispatch_ewma_s = 0.005
        self._running = True
        METRICS.gauge("devices").set(self.n_devices)
        METRICS.gauge("depth").set(self.depth)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="mesh-executor")
        self._thread.start()

    # ------------------------------------------------------ program cache
    def dispatch_width(self, width: int) -> int:
        """A lane's mesh dispatch width: the per-device batch times the
        mesh size (every device gets the single-chip batch the
        submitter tuned for), bounded, and always a mesh multiple."""
        w = max(1, int(width)) * self.n_devices
        w = min(w, MAX_DISPATCH_WIDTH)
        return max(self.n_devices, -(-w // self.n_devices) * self.n_devices)

    def accepts(self, key: tuple) -> bool:
        """Whether `key` resolves to a mesh program (spill eligibility).
        May build (and on device backends compile) the program."""
        return self._resolve(key) is not None

    def accepts_cached(self, key: tuple) -> Optional[bool]:
        """Non-blocking spill-eligibility peek: True/False when `key`
        has already been resolved, None when unknown — callers holding
        their own dispatch locks use this and warm unknown keys via
        `accepts()` outside them (resolution may compile)."""
        with self._lock:
            if key not in self._programs:
                return None
            return self._programs[key] is not None

    def _resolve(self, key: tuple) -> Optional[_MeshProgram]:
        with self._lock:
            if key in self._programs:
                return self._programs[key]
        try:
            prog = self._build_program(key)
        except Exception:  # noqa: BLE001 - unresolvable key: caller keeps its single-chip path
            log.exception("mesh program resolution failed for %r", key)
            prog = None
        with self._lock:
            self._programs.setdefault(key, prog)
            return self._programs[key]

    def _build_program(self, key: tuple) -> Optional[_MeshProgram]:
        from ozone_tpu.codec import fused

        kind = key[0]
        if kind == "encode":
            spec = key[1]
            if fused._prefer_host_coder(spec.options,
                                        checksum=spec.checksum):
                single = fused._native_fused_encoder(
                    spec.options, spec.checksum, spec.bytes_per_checksum)
                if single is not None:
                    return _MeshProgram(self._host_shard(single), (), True)
            jfn = sharded.make_sharded_fused_encoder(
                spec, self.mesh, self.axis)
            return _MeshProgram(jfn, (jfn,), False)
        if kind == "decode":
            spec, valid, erased = key[1], list(key[2]), list(key[3])
            out_ratio = len(erased) / max(len(valid), 1)
            if fused._prefer_host_coder(spec.options, out_ratio=out_ratio,
                                        checksum=spec.checksum):
                single = fused._native_fused_decoder(
                    spec.options, spec.checksum, spec.bytes_per_checksum,
                    tuple(valid), tuple(erased))
                if single is not None:
                    return _MeshProgram(self._host_shard(single), (), True)
            jfn = sharded.make_sharded_decoder(
                spec, valid, erased, self.mesh, self.axis)
            k_dev, zeros_crc = fused.crc_plan_cached(
                spec.checksum, spec.bytes_per_checksum)
            apply_fn = sharded._sharded_decode_apply_cached(
                self.mesh, self.axis, k_dev is not None, zeros_crc)
            return _MeshProgram(jfn, (apply_fn,), False)
        # reencode and custom fns have no sharded twin (the re-encode
        # kernel's single fused dispatch doesn't decompose across the
        # batch axis for free) — their lanes never spill here
        return None

    def _host_shard(self, single: Callable) -> Callable:
        """Shard a batch across one worker thread per mesh device, each
        running the native single-chip twin on its contiguous slice —
        the host mirror of the DP sharding (batch axis over devices)."""
        n = self.n_devices

        def fn(batch: np.ndarray):
            per = batch.shape[0] // n
            if per == 0:
                outs = [single(batch)]
            else:
                futs = [
                    self._workers.submit(single, batch[i * per:(i + 1) * per])
                    for i in range(n)
                ]
                outs = [f.result() for f in futs]
            first = outs[0] if isinstance(outs[0], tuple) else (outs[0],)
            width = len(first)
            return tuple(
                np.concatenate(
                    [(o if isinstance(o, tuple) else (o,))[i]
                     for o in outs], axis=0)
                for i in range(width))

        return fn

    # ---------------------------------------------------------- staging
    def _take_staging(self, shape: tuple, dtype) -> np.ndarray:
        skey = (shape, np.dtype(dtype).str)
        with self._lock:
            free = self._staging.get(skey)
            if free:
                METRICS.counter("staging_reuses").inc()
                return free.pop()
        return np.empty(shape, dtype=dtype)

    def _give_staging(self, buf: np.ndarray) -> None:
        skey = (buf.shape, buf.dtype.str)
        with self._lock:
            free = self._staging.setdefault(skey, [])
            if len(free) <= self.depth:
                free.append(buf)

    # ----------------------------------------------------------- submit
    def submit(self, key: tuple, stripes: np.ndarray, *, width: int,
               qos: str = "bulk", tail: bool = False,
               deadline=None) -> Future:
        """Enqueue `stripes` ([n, ...], n >= 1) under semantic `key`.

        `width` is the submitter's per-device batch width (the lane
        dispatches at ``dispatch_width(width)``). Raises KeyError when
        the key has no mesh program — callers should have checked
        `accepts()` or hold a pipeline from `pipeline()`.
        """
        if stripes.shape[0] < 1:
            raise ValueError("empty mesh submission")
        prog = self._resolve(key)
        if prog is None:
            raise KeyError(f"no mesh program for {key!r}")
        if deadline is None:
            deadline = _ambient_deadline()
        fut: Future = Future()
        sub = _Sub(stripes, fut, qos, deadline, tail)
        self._enqueue(key, prog, width, qos, [sub])
        return fut

    def _enqueue(self, key: tuple, prog: _MeshProgram, width: int,
                 qos: str, subs: list) -> None:
        lane_key = (key, int(width), qos)
        lane_width = self.dispatch_width(width)
        with self._cond:
            if not self._running:
                raise RuntimeError("mesh executor is shut down")
            lane = self._lanes.get(lane_key)
            if lane is None:
                lane = self._lanes[lane_key] = _Lane(
                    lane_key, prog, lane_width, qos)
            for sub in subs:
                lane.subs.append(sub)
                lane.queued += sub.n
                lane.min_deadline_t = min(lane.min_deadline_t,
                                          sub.deadline_t())
                METRICS.counter("submissions").inc()
            METRICS.gauge("queue_depth").set(self._queue_depth_locked())
            self._cond.notify()

    def absorb(self, key: tuple, width: int, qos: str,
               subs: list) -> None:
        """Take over queued submissions spilled from the codec service:
        same future, same stripes, same deadline — only the dispatch
        path changes. Caller guarantees no sub has partially-dispatched
        stripes (the service only spills untouched lanes)."""
        prog = self._resolve(key)
        if prog is None:
            raise KeyError(f"no mesh program for {key!r}")
        METRICS.counter("spilled_lanes").inc()
        METRICS.counter("spilled_stripes").inc(sum(s.n for s in subs))
        self._enqueue(key, prog, width, qos, subs)

    def pipeline(self, key: tuple, *, width: int,
                 qos: str = "bulk") -> "MeshPipeline":
        """A `ServicePipeline`-shaped front end over one mesh lane —
        the two-line routing change for depth-1 pipeline consumers.
        Raises KeyError when the key has no mesh program."""
        if self._resolve(key) is None:
            raise KeyError(f"no mesh program for {key!r}")
        return MeshPipeline(self, key, width=width, qos=qos)

    # ------------------------------------------------------- scheduling
    def _queue_depth_locked(self) -> int:
        return sum(lane.queued for lane in self._lanes.values())

    def _flush_margin_s(self) -> float:
        return self.linger_s + 4.0 * self._dispatch_ewma_s

    def _ready_lane_locked(self, now: float) -> Optional[_Lane]:
        """Earliest-deadline-then-oldest ready lane: full lanes first,
        then deadline-pressed, then lingered-out. The heavy fairness
        machinery (WFQ vtime, starvation guard) lives in the codec
        service front end; by the time work reaches the mesh it is
        bulk-classed or already fairness-filtered."""
        best: Optional[_Lane] = None
        best_rank: tuple = ()
        margin = self._flush_margin_s()
        for lane in self._lanes.values():
            if not lane.subs:
                continue
            head_age = now - lane.subs[0].t_enq
            if lane.queued >= lane.width:
                rank = (0, -lane.queued, lane.subs[0].t_enq)
            elif lane.min_deadline_t - now <= margin:
                rank = (1, lane.min_deadline_t, lane.subs[0].t_enq)
            elif head_age >= self.linger_s:
                rank = (2, lane.subs[0].t_enq, 0.0)
            else:
                continue
            if best is None or rank < best_rank:
                best, best_rank = lane, rank
        return best

    def _next_wakeup_locked(self, now: float) -> Optional[float]:
        t = math.inf
        margin = self._flush_margin_s()
        for lane in self._lanes.values():
            if not lane.subs:
                continue
            t = min(t, lane.subs[0].t_enq + self.linger_s,
                    lane.min_deadline_t - margin)
        return None if math.isinf(t) else max(0.0, t - now)

    def _pack_locked(self, lane: _Lane):
        entries: list[tuple[_Sub, int, int, int]] = []
        row = 0
        while lane.subs and row < lane.width:
            sub = lane.subs[0]
            take = min(sub.n - sub.taken, lane.width - row)
            entries.append((sub, sub.taken, take, row))
            sub.taken += take
            sub.pending_parts += 1
            if sub.taken == sub.n:
                lane.subs.popleft()
            row += take
            lane.queued -= take
        if not lane.subs:
            lane.min_deadline_t = math.inf
        else:
            lane.min_deadline_t = min(s.deadline_t() for s in lane.subs)
        return entries, row

    # ------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        try:
            while True:
                entries = None
                with self._cond:
                    now = time.monotonic()
                    lane = self._ready_lane_locked(now)
                    if lane is not None:
                        entries, rows = self._pack_locked(lane)
                    elif not self._inflight:
                        if not self._running:
                            if not self._lanes or not any(
                                    ln.subs for ln in self._lanes.values()):
                                break
                            lane = next(ln for ln in self._lanes.values()
                                        if ln.subs)
                            entries, rows = self._pack_locked(lane)
                        else:
                            self._cond.wait(self._next_wakeup_locked(now))
                            continue
                if entries is not None:
                    self._dispatch(lane, entries, rows)
                    # depth-N buffering: keep up to `depth` mesh batches
                    # in flight; harvest the oldest only once the window
                    # is over-full, so launches never wait on pulls
                    while len(self._inflight) > self.depth:
                        self._complete(self._inflight.popleft())
                elif self._inflight:
                    # nothing packable: never hold results hostage
                    self._complete(self._inflight.popleft())
        except BaseException:  # noqa: BLE001 - dispatcher must not die silently
            log.exception("mesh executor dispatcher crashed")
            raise
        finally:
            with self._lock:
                self._running = False
            self._fail_pending(RuntimeError("mesh executor stopped"))

    def _dispatch(self, lane: _Lane, entries, rows: int) -> None:
        now = time.monotonic()
        ops = len(entries)
        tracer = Tracer.instance()
        lane_desc = str(lane.lane_key)[:120]
        for sub, off, take, _row in entries:
            if off == 0:
                wait = now - sub.t_enq
                tid = sub.trace_ctx.split(":", 1)[0]
                METRICS.histogram("queue_wait_seconds").observe(wait, tid)
                if sub.trace_ctx:
                    tracer.record_span(
                        "mesh:queue_wait", child_of=sub.trace_ctx,
                        start=sub.t_enq_wall, duration=wait,
                        lane=lane_desc, qos=sub.cls)
        head = entries[0]
        staged = None
        if ops == 1 and head[2] == rows == lane.width and head[1] == 0 \
                and head[0].n == lane.width \
                and head[0].stripes.flags.c_contiguous:
            # one submission covering the whole batch: dispatch its own
            # rows without a staging copy
            batch = head[0].stripes
        else:
            shape = (lane.width,) + tuple(head[0].stripes.shape[1:])
            staged = batch = self._take_staging(
                shape, head[0].stripes.dtype)
            for sub, off, take, row in entries:
                batch[row:row + take] = sub.stripes[off:off + take]
            if rows < lane.width:
                batch[rows:] = 0  # constant-shape zero-padded tail
        t0 = time.monotonic()
        with tracer.span("mesh:dispatch", lane=lane_desc, ops=ops,
                         rows=rows, width=lane.width,
                         devices=self.n_devices):
            try:
                outs = lane.program.fn(batch)
            except BaseException as e:  # noqa: BLE001 - per-dispatch fault
                if staged is not None:
                    self._give_staging(staged)
                self._resolve_error(entries, e)
                return
            if not isinstance(outs, tuple):
                outs = (outs,)
            for a in outs:
                # eager D2H: the pull overlaps the next batch's staging
                _start_d2h(a)
        METRICS.counter("dispatches").inc()
        METRICS.counter("stripes_dispatched").inc(rows)
        METRICS.counter("slots_dispatched").inc(lane.width)
        METRICS.counter("coalesced_operations").inc(ops)
        if ops > 1:
            METRICS.counter("multi_op_dispatches").inc()
        METRICS.gauge("batch_fill_pct").set(100.0 * rows / lane.width)
        with self._lock:
            METRICS.gauge("queue_depth").set(self._queue_depth_locked())
        self._inflight.append(
            (entries, outs, staged, t0, time.time(),
             (lane_desc, ops, rows, lane.width)))
        depth_now = len(self._inflight)
        self._max_inflight = max(self._max_inflight, depth_now)
        METRICS.gauge("inflight_depth").set(depth_now)
        METRICS.gauge("inflight_per_device").set(depth_now)
        METRICS.gauge("max_inflight_depth").set(self._max_inflight)

    def _complete(self, rec: tuple) -> None:
        entries, outs, staged, t0, t0_wall, dctx = rec
        lane_desc, ops, rows, width = dctx
        try:
            host = tuple(np.asarray(a) for a in outs)
        except BaseException as e:  # noqa: BLE001 - D2H fault
            if staged is not None:
                self._give_staging(staged)
            self._resolve_error(entries, e)
            return
        if staged is not None:
            self._give_staging(staged)
        dt = time.monotonic() - t0
        self._dispatch_ewma_s += 0.2 * (dt - self._dispatch_ewma_s)
        METRICS.histogram("dispatch_seconds").observe(
            dt, entries[0][0].trace_ctx.split(":", 1)[0])
        METRICS.gauge("inflight_depth").set(len(self._inflight))
        tracer = Tracer.instance()
        for sub, off, take, _row in entries:
            if sub.trace_ctx:
                tracer.record_span(
                    "mesh:device_dispatch", child_of=sub.trace_ctx,
                    start=t0_wall, duration=dt, lane=lane_desc,
                    qos=sub.cls, stripes=take, ops=ops, rows=rows,
                    width=width)
        for sub, off, take, row in entries:
            sub.parts.append(
                (off, take, tuple(a[row:row + take] for a in host)))
            sub.pending_parts -= 1
            if sub.taken == sub.n and sub.pending_parts == 0:
                _resolve_sub(sub)

    @staticmethod
    def _resolve_error(entries, e: BaseException) -> None:
        done = set()
        for sub, _off, _take, _row in entries:
            if id(sub) not in done:
                done.add(id(sub))
                if not sub.future.done():
                    sub.future.set_exception(e)

    def _fail_pending(self, e: BaseException) -> None:
        with self._lock:
            subs = [s for lane in self._lanes.values() for s in lane.subs]
            self._lanes.clear()
            inflight, self._inflight = list(self._inflight), deque()
        for rec in inflight:
            for sub, _o, _t, _r in rec[0]:
                subs.append(sub)
        for s in subs:
            if not s.future.done():
                s.future.set_exception(e)

    # ---------------------------------------------------------- control
    def compile_counts(self) -> int:
        """Total compiled executables across every resolved mesh
        program — the warm-program proof probes the delta of this
        across steady-state rounds (must be zero)."""
        with self._lock:
            progs = [p for p in self._programs.values() if p is not None]
        return sum(p.compile_count() for p in progs)

    def stats(self) -> dict:
        """Operator snapshot (the Recon /api/mesh payload)."""
        snap = METRICS.snapshot()
        slots = snap.get("slots_dispatched", 0)
        disp = snap.get("dispatches", 0)
        snap["fill_ratio"] = (snap.get("stripes_dispatched", 0) / slots
                              if slots else 0.0)
        snap["ops_per_dispatch"] = (
            snap.get("coalesced_operations", 0) / disp if disp else 0.0)
        with self._lock:
            snap["queue_depth"] = self._queue_depth_locked()
            snap["lanes"] = len(self._lanes)
            snap["inflight"] = len(self._inflight)
            progs = [p for p in self._programs.values() if p is not None]
            snap["programs"] = len(progs)
            snap["programs_host_twin"] = sum(
                1 for p in progs if p.host_twin)
        snap["max_inflight"] = self._max_inflight
        snap["devices"] = self.n_devices
        snap["mesh_depth"] = self.depth
        snap["compile_counts"] = sum(p.compile_count() for p in progs)
        snap["spill_enabled"] = spill_enabled()
        snap["spill_watermark"] = spill_watermark()
        snap["enabled"] = enabled()
        return snap

    def quiesce(self, timeout_s: float = 30.0) -> None:
        """Wait until every queued submission has dispatched and
        harvested (tests and drills; production never needs it)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            with self._lock:
                if not self._inflight and \
                        self._queue_depth_locked() == 0:
                    return
            time.sleep(0.002)

    def close(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout=60.0)
        self._fail_pending(RuntimeError("mesh executor shut down"))
        self._workers.shutdown(wait=False)


def _resolve_sub(sub: _Sub) -> None:
    if sub.future.done():
        return
    if len(sub.parts) == 1:
        sub.future.set_result(sub.parts[0][2])
        return
    sub.parts.sort(key=lambda p: p[0])
    outs = tuple(
        np.concatenate([p[2][i] for p in sub.parts], axis=0)
        for i in range(len(sub.parts[0][2])))
    sub.future.set_result(outs)


class MeshPipeline:
    """Drop-in twin of `DeviceBatchPipeline`/`ServicePipeline` backed by
    one mesh lane: submit(batch, ctx) coalesces into full-width mesh
    dispatches and returns the PREVIOUS submission's host results."""

    def __init__(self, executor: MeshExecutor, key: tuple, *,
                 width: int, qos: str = "bulk"):
        self._ex = executor
        self._key = key
        self._width = max(1, int(width))
        self._qos = qos
        self._pending: Optional[tuple] = None

    def submit(self, batch: np.ndarray, ctx: Any = None,
               tail: bool = False) -> Optional[tuple]:
        fut = self._ex.submit(self._key, batch, width=self._width,
                              qos=self._qos, tail=tail)
        prev, self._pending = self._pending, (ctx, fut)
        return self._to_host(prev)

    def drain(self) -> Optional[tuple]:
        prev, self._pending = self._pending, None
        return self._to_host(prev)

    @staticmethod
    def _to_host(entry: Optional[tuple]) -> Optional[tuple]:
        if entry is None:
            return None
        ctx, fut = entry
        from ozone_tpu.codec import service as codec_service

        return ctx, codec_service.wait_result(fut)


_executor: Optional[MeshExecutor] = None
_executor_lock = threading.Lock()


def get_executor() -> MeshExecutor:
    """The process-wide executor (created on first use)."""
    global _executor
    with _executor_lock:
        if _executor is None or not _executor._running:
            _executor = MeshExecutor()
        return _executor


def maybe_executor() -> Optional[MeshExecutor]:
    """The executor when it can exist here: enabled AND more than one
    device attached — the ONE check routed datapaths (lifecycle mesh
    lane, reconstruction storms, codec-service spill) make before
    falling back to their single-chip pipelines."""
    if not enabled():
        return None
    try:
        import jax

        if jax.device_count() < 2:
            return None
    except Exception:  # noqa: BLE001 - no backend: single-device path
        return None
    try:
        return get_executor()
    except Exception:  # noqa: BLE001 - mesh construction failed: fall back
        log.exception("mesh executor unavailable")
        return None


def reset_for_tests() -> None:
    """Shut down and drop the singleton (fresh knobs per test)."""
    global _executor
    with _executor_lock:
        ex, _executor = _executor, None
    if ex is not None:
        ex.close()
