"""Multi-host device meshes: the distributed comm backend.

Role analog of the reference's Ratis/gRPC-spanning cluster fabric on
the COMPUTE side: where the reference scales its datapath across hosts
with its own RPC fan-out, the codec/reconstruction compute here scales
across hosts the JAX way — one `jax.distributed` runtime connects the
processes, `jax.devices()` becomes the global device set, and XLA
inserts the collectives (psum/all_gather/ppermute) so they ride ICI
within a host and DCN across hosts (the scaling-book recipe; no NCCL/
MPI calls to port).

Everything in parallel/sharded.py is topology-agnostic: the meshes
built here drop into `make_sharded_fused_encoder`, `make_ring_decoder`,
the reconstruction coordinator's `mesh=` argument, and
`ECBlockGroupReader(mesh=...)` unchanged — a coordinator running on a
multi-host TPU slice reconstructs with the SAME code the single-host
tests exercise.

Wire-up on a v5e-style slice (one process per host):

    from ozone_tpu.parallel import multihost
    multihost.initialize("10.0.0.1:8476", num_processes=4, process_id=i)
    mesh = multihost.global_codec_mesh()          # 1-D, all devices
    hybrid = multihost.hybrid_codec_mesh()        # ("dcn", "dn") 2-D

`tests/test_multihost.py` proves the path end-to-end without TPU
hardware: two OS processes × four virtual CPU devices each form one
8-device global mesh and run the sharded fused encoder on it,
asserting bit-exact parity against the host coder.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from jax.sharding import Mesh


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_count: Optional[int] = None) -> None:
    """Join this process to the cluster-wide JAX runtime (the comm-
    backend bootstrap; NCCL/MPI-init analog). Process 0 hosts the
    coordination service; every process calls this before touching
    devices. Idempotent per process."""
    if local_device_count is not None:
        # CPU hosts: carve the process into N virtual devices FIRST so
        # the global device set is consistent across the cluster
        import os

        flags = os.environ.get("XLA_FLAGS", "")
        want = f"--xla_force_host_platform_device_count={local_device_count}"
        if want not in flags:
            os.environ["XLA_FLAGS"] = f"{flags} {want}".strip()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def global_codec_mesh(axis: str = "dn") -> Mesh:
    """1-D mesh over EVERY device in the cluster (all processes), the
    shape the DP fused encoder and the survivor ring shard over. Device
    order is jax's global enumeration — process-major, so neighbouring
    ring stages stay on-host where possible (ppermute hops ride ICI
    first, DCN only at host boundaries)."""
    devs = jax.devices()
    return Mesh(np.array(devs), (axis,))


def hybrid_codec_mesh(ici_axis: str = "dn",
                      dcn_axis: str = "dcn") -> Mesh:
    """2-D (dcn, dn) mesh: the cross-host axis outermost, devices of
    one host contiguous on the inner axis — the layout where sharding
    batch over `dcn` and units over `dn` keeps the heavy all-to-alls
    on ICI and only batch-sharded (communication-free) work across DCN
    (mesh_utils.create_hybrid_device_mesh semantics, hand-rolled so
    CPU-device test rigs work too)."""
    devs = jax.devices()
    n_proc = max(d.process_index for d in devs) + 1
    counts = [0] * n_proc
    for d in devs:
        counts[d.process_index] += 1
    per = len(devs) // n_proc
    if any(c != per for c in counts):
        raise ValueError(f"uneven devices per process: {counts}")
    grid = np.empty((n_proc, per), dtype=object)
    fill = [0] * n_proc
    for d in devs:
        p = d.process_index
        grid[p, fill[p]] = d
        fill[p] += 1
    return Mesh(grid, (dcn_axis, ici_axis))


def process_count() -> int:
    return jax.process_count()


def is_coordinator() -> bool:
    return jax.process_index() == 0
