"""Multi-chip sharded EC codec: jit + shard_map over a device mesh.

The distribution story of the TPU build (SURVEY.md section 2 "distribution
strategies" and BASELINE config #5 — multi-datanode reconstruction with
parity work sharded over v5e-8 ICI):

- **Stripe parallelism (DP)**: the stripe batch axis is sharded over the
  mesh; encode/decode+CRC run with zero cross-chip traffic. This is the
  production path for bulk encode and multi-block reconstruction — the
  structural analog of the reference running one reconstruction task per
  datanode (ECReconstructionCoordinator) but with the batch spread over
  chips instead of threads.

- **Unit parallelism (TP)**: the k data units are sharded over the mesh;
  each chip computes a partial GF(2) sum against its slice of the coding
  matrix and an int32 psum over ICI accumulates before the mod-2. XOR-
  accumulate distributes over psum because parity bits are sums mod 2 and
  integer addition commutes with the final &1. Used when single stripes
  are huge (cell >> HBM/chip) — the analog of splitting one stripe's
  coding work across nodes.

- **Ring reconstruction (SP)**: the k surviving units are sharded one
  group per chip — the natural layout when each chip fronts one datanode
  of the reconstruction read fan-in (ECReconstructionCoordinator reads k
  survivors in parallel; here each survivor's bytes land on a different
  chip). Each chip computes its packed-byte partial parity and the
  partials ride an explicit ppermute ring, XOR-combining at every hop
  (the ring-attention pattern applied to GF(2) coding: XOR is the
  mod-2 reduction, so packed uint8 partials — not bit-planes, not int32
  sums — are the ring payload, 32x less ICI traffic than a naive int32
  psum of bit-planes).

All collectives are XLA collectives over the mesh (psum / ppermute); no
host-side communication is involved.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 top-level export
    from jax import shard_map
except ImportError:  # older jax: experimental namespace, same semantics
    from jax.experimental.shard_map import shard_map

import inspect

#: the replication-checker toggle was renamed check_rep -> check_vma
#: across jax versions; resolve the name this jax actually accepts
_CHECK_KW = ("check_vma"
             if "check_vma" in inspect.signature(shard_map).parameters
             else "check_rep")

from ozone_tpu.codec import crc_device
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.bitlin import expand_coding_matrix
from ozone_tpu.codec.fused import (
    FusedSpec,
    _POLY,
    _decode_matrix,
    _parity_matrix,
    crc_plan_cached,
)
from ozone_tpu.codec.jax_coder import (
    _gf_dot,
    bits_to_bytes,
    bytes_to_bits,
    gf_apply,
    pack_bit_rows,
)
from ozone_tpu.utils.checksum import ChecksumType


def make_mesh(
    n_devices: Optional[int] = None, axis: str = "dn"
) -> Mesh:
    """1-D mesh over the first n devices (default: all)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def default_codec_mesh(axis: str = "dn") -> Optional[Mesh]:
    """Production mesh policy: all local devices when more than one is
    attached, None (single-chip fused path) otherwise. Datanode daemons
    and the minicluster hand this to the reconstruction coordinator and
    scrubber so multi-chip hosts repair/scrub across every chip without
    configuration."""
    try:
        n = jax.device_count()
    except Exception:  # noqa: BLE001 - no backend: single-device path
        return None
    return make_mesh(axis=axis) if n > 1 else None


def pad_batch(batch: np.ndarray, n: int) -> tuple[np.ndarray, int]:
    """Pad the leading axis to a multiple of n; returns (padded, original)."""
    b = batch.shape[0]
    rem = (-b) % n
    if rem:
        pad = np.zeros((rem,) + batch.shape[1:], dtype=batch.dtype)
        batch = np.concatenate([batch, pad], axis=0)
    return batch, b


# --------------------------------------------------------------------- DP
@lru_cache(maxsize=16)
def _sharded_fused_encoder_cached(
    options: CoderOptions,
    checksum: ChecksumType,
    bpc: int,
    mesh: Mesh,
    axis: str,
):
    a = jnp.asarray(
        expand_coding_matrix(_parity_matrix(options)),
        dtype=jnp.int8,
    )
    if checksum in _POLY:
        k_np, zeros_crc = crc_device.crc_constants_planemajor(
            bpc, _POLY[checksum]
        )
        k_dev = jnp.asarray(k_np)
    else:
        k_dev, zeros_crc = None, 0

    batch_sharding = NamedSharding(mesh, P(axis))

    def fn(data):
        parity = gf_apply(data, a)
        if k_dev is None:
            crcs = jnp.zeros(
                (data.shape[0], data.shape[1] + parity.shape[1], 0), jnp.uint32
            )
        else:
            crcs = jnp.concatenate(
                [
                    crc_device.crc_slices(data, k_dev, zeros_crc),
                    crc_device.crc_slices(parity, k_dev, zeros_crc),
                ],
                axis=1,
            )
        return parity, crcs

    return jax.jit(
        fn,
        in_shardings=batch_sharding,
        out_shardings=(batch_sharding, batch_sharding),
    )


def make_sharded_fused_encoder(spec: FusedSpec, mesh: Mesh, axis: str = "dn"):
    """Stripe-parallel fused encode+CRC: fn(data [B, k, C]) with B sharded
    over the mesh; B must divide by mesh size (see pad_batch)."""
    return _sharded_fused_encoder_cached(
        spec.options, spec.checksum, spec.bytes_per_checksum, mesh, axis
    )


@lru_cache(maxsize=16)
def _sharded_decode_apply_cached(mesh: Mesh, axis: str, with_crc: bool,
                                 zeros_crc: int):
    """One sharded decode+CRC executable per (mesh, shape): the recovery
    matrix and CRC constants arrive as traced, mesh-replicated arguments
    (the fused._decode_apply_jit treatment with explicit shardings), so
    erasure-pattern churn during multi-unit failures never recompiles
    the SPMD program — only the tiny replicated matrix changes."""
    batch_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    if not with_crc:
        def fn_nocrc(valid_units, a):
            rec = gf_apply(valid_units, a)
            return rec, jnp.zeros(rec.shape[:2] + (0,), jnp.uint32)

        return jax.jit(
            fn_nocrc,
            in_shardings=(batch_sharding, replicated),
            out_shardings=(batch_sharding, batch_sharding),
        )

    def fn(valid_units, a, k_dev):
        rec = gf_apply(valid_units, a)
        crcs = crc_device.crc_slices(rec, k_dev, zeros_crc)
        return rec, crcs

    return jax.jit(
        fn,
        in_shardings=(batch_sharding, replicated, replicated),
        out_shardings=(batch_sharding, batch_sharding),
    )


@lru_cache(maxsize=512)
def _sharded_decode_plan_cached(
    options: CoderOptions, valid: tuple, erased: tuple,
):
    """Per-pattern decode matrix for the sharded path; cheap host work,
    shared executable above, CRC constants shared via
    fused.crc_plan_cached."""
    dm = _decode_matrix(options, list(valid), list(erased))
    return jnp.asarray(expand_coding_matrix(dm), dtype=jnp.int8)


def make_sharded_decoder(
    spec: FusedSpec, valid: list[int], erased: list[int], mesh: Mesh,
    axis: str = "dn",
):
    """Stripe-parallel fused decode+CRC (multi-chip reconstruction path).
    Pattern-count-proof like the single-chip path: one compiled SPMD
    program per shape serves every (valid, erased) pattern."""
    a = _sharded_decode_plan_cached(
        spec.options, tuple(valid), tuple(erased))
    k_dev, zeros_crc = crc_plan_cached(spec.checksum,
                                       spec.bytes_per_checksum)
    apply_fn = _sharded_decode_apply_cached(
        mesh, axis, k_dev is not None, zeros_crc)
    if k_dev is None:
        return lambda valid_units: apply_fn(valid_units, a)
    return lambda valid_units: apply_fn(valid_units, a, k_dev)


# --------------------------------------------------------------------- TP
@lru_cache(maxsize=16)
def _tp_encoder_cached(options: CoderOptions, mesh: Mesh, axis: str):
    k, p = options.data_units, options.parity_units
    n = mesh.devices.size
    if k % n:
        raise ValueError(f"TP encode requires k % mesh == 0, got {k} % {n}")
    a_np = expand_coding_matrix(_parity_matrix(options))  # [k*8, p*8]
    a = jnp.asarray(a_np, dtype=jnp.int8)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis, None), P(axis, None)),
        out_specs=P(None, None, None),
    )
    def tp_encode(data_local, a_local):
        # data_local [B, k/n, C]; a_local [k*8/n, p*8]
        bits = bytes_to_bits(data_local)  # [B, (k/n)*8, C]
        partial_acc = jax.lax.dot_general(
            a_local.T,
            bits,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [p*8, B, C] partial integer sums
        total = jax.lax.psum(partial_acc, axis)  # ICI collective
        pbits = jnp.moveaxis(jnp.bitwise_and(total, 1), 0, -2).astype(jnp.int8)
        return bits_to_bytes(pbits)  # [B, p, C] replicated

    return jax.jit(lambda d: tp_encode(d, a))


def make_tp_encoder(options: CoderOptions, mesh: Mesh, axis: str = "dn"):
    """Unit-parallel encode: data units sharded over the mesh, parity
    accumulated with psum over ICI. fn(data [B, k, C]) -> parity [B, p, C]."""
    return _tp_encoder_cached(options, mesh, axis)


# ------------------------------------------------------------------- ring
@lru_cache(maxsize=512)
def _ring_decode_plan_cached(
    options: CoderOptions, valid: tuple, erased: tuple, n: int,
):
    """Per-pattern ring plan: the decode matrix zero-padded to the
    mesh's survivor slots. Cheap host work; the compiled SPMD program
    lives in _ring_apply_cached and serves every pattern of a shape."""
    k = len(valid)
    e = len(erased)
    upc = -(-k // n)  # units per chip, survivors zero-padded to upc * n
    dm = _decode_matrix(options, list(valid), list(erased))  # GF [e, k]
    a_np = expand_coding_matrix(dm)  # [k*8, e*8]
    if upc * n != k:
        # zero matrix rows for the padded survivor slots: a zero unit
        # contributes a zero partial, keeping the ring XOR exact
        a_np = np.concatenate(
            [a_np, np.zeros(((upc * n - k) * 8, e * 8), dtype=a_np.dtype)]
        )
    return jnp.asarray(a_np, dtype=jnp.int8), upc


@lru_cache(maxsize=16)
def _ring_apply_cached(mesh: Mesh, axis: str, with_crc: bool,
                       zeros_crc: int):
    """One ring-decode executable per (mesh, shape): like the DP path,
    the padded recovery matrix arrives as a traced argument (sharded
    over survivors), so erasure-pattern churn never recompiles the
    SPMD ring program."""
    n = mesh.devices.size
    perm = [(i, (i + 1) % n) for i in range(n)]

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(None, axis, None), P(axis, None)),
        out_specs=P(None, None, None),
        # replication checker off (check_vma / legacy check_rep — the
        # name this jax accepts, resolved at import): the output IS
        # replicated, but only by a dynamic argument — after n-1
        # ppermute hops every chip has XOR-accumulated all n partials
        # (each hop k adds the partial that originated k chips
        # upstream), so all chips hold the same XOR-of-all-partials.
        # The static replication checker cannot prove properties that
        # depend on the permutation completing a cycle; the dryrun
        # asserts cross-device equality of this output at runtime
        # (__graft_entry__.dryrun_multichip).
        **{_CHECK_KW: False},
    )
    def ring_decode(units_local, a_local):
        # units_local [B, upc, C] uint8; a_local [upc*8, e*8] int8
        pbits = _gf_dot(bytes_to_bits(units_local), a_local)  # [e*8, B, C]
        # pack the PARTIAL parity to bytes before touching the ring: XOR
        # of packed bytes == packed XOR of bits, so the ring payload is
        # [e, B, C] uint8 — 8x smaller than bit-planes
        local = pack_bit_rows(pbits)  # [e, B, C]
        acc_ring = local
        for _ in range(n - 1):
            acc_ring = (
                jax.lax.ppermute(acc_ring, axis, perm) ^ local
            )
        return jnp.moveaxis(acc_ring, 0, 1)  # [B, e, C] replicated

    batch_sharding = NamedSharding(mesh, P(axis))

    if not with_crc:
        def inner_nocrc(valid_units, a):
            rec = ring_decode(valid_units, a)
            return rec, jnp.zeros(rec.shape[:2] + (0,), jnp.uint32)

        return jax.jit(inner_nocrc)

    def inner(valid_units, a, k_dev):
        rec = ring_decode(valid_units, a)
        # the ring output is replicated; shard the CRC pass over the
        # stripe batch so the checksum work spreads over the mesh
        # instead of running n-fold redundantly
        rec_sh = jax.lax.with_sharding_constraint(rec, batch_sharding)
        crcs = crc_device.crc_slices(rec_sh, k_dev, zeros_crc)
        return rec, crcs

    return jax.jit(inner)


def make_ring_decoder(
    spec: FusedSpec, valid: list[int], erased: list[int], mesh: Mesh,
    axis: str = "dn",
):
    """Survivor-sharded ring reconstruction: fn(valid_units [B, k, C]) ->
    (recovered [B, e, C], crcs). The k survivor units are sharded over the
    mesh (zero-padded to a multiple of its size); packed-byte partial
    parities XOR-combine around a ppermute ring. The multi-datanode
    reconstruction layout of BASELINE config #5: each chip ingests one
    survivor datanode's bytes, no chip ever holds the whole stripe.
    Pattern-count-proof like the DP path: the padded decode matrix is a
    per-pattern plan fed to ONE compiled ring program per shape."""
    n = mesh.devices.size
    a, upc = _ring_decode_plan_cached(
        spec.options, tuple(valid), tuple(erased), n)
    k_dev, zeros_crc = crc_plan_cached(spec.checksum,
                                       spec.bytes_per_checksum)
    apply_fn = _ring_apply_cached(mesh, axis, k_dev is not None, zeros_crc)

    def fn(valid_units):
        b, kk, c = valid_units.shape
        if kk != upc * n:
            # pad OUTSIDE the jitted program: inside it, the zeros pad
            # is a broadcast whose unit axis (size upc*n-kk < n) cannot
            # take the survivor sharding, forcing XLA's SPMD partitioner
            # into an involuntary full rematerialization
            # (replicate-then-repartition) — the round-1 dryrun warning.
            # jnp (not np) keeps the wrapper traceable and device arrays
            # on device; the jit call boundary below shards the result.
            pad = jnp.zeros((b, upc * n - kk, c), dtype=valid_units.dtype)
            valid_units = jnp.concatenate(
                [jnp.asarray(valid_units), pad], axis=1)
        return (apply_fn(valid_units, a) if k_dev is None
                else apply_fn(valid_units, a, k_dev))

    return fn
