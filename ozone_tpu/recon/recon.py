"""Recon: cluster observability warehouse + REST API.

Mirror of the reference's Recon service (hadoop-ozone/recon ReconServer:
an OM-metadata follower feeding aggregation tasks — ContainerKeyMapperTask,
FileSizeCountTask, NSSummaryTask — plus a passive SCM view detecting
missing/under-replicated containers, exposed over REST for operators and
the UI). Here: tasks run over a snapshot/tail of the OM store and the SCM
object's live state, materializing

  - namespace summary (volumes/buckets/keys, bytes)
  - file-size histogram (FileSizeCountTask analog)
  - container -> key mapping (ContainerKeyMapperTask analog)
  - container health: missing / under- / over-replicated (fsck view)
  - node utilization table

served as JSON endpoints on the service HTTP server (/api/...).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Optional

from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.pipeline import ReplicationType
from ozone_tpu.scm.replication_manager import ECReplicaCount
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.storage.ids import ContainerState


class ReconTasks:
    """Aggregation tasks over OM metadata (ReconOmTask pipeline analog)."""

    def __init__(self, om: OzoneManager):
        self.om = om

    def namespace_summary(self) -> dict:
        """Namespace totals plus per-bucket heat cells — one walk serves
        both the summary tiles and the heatmap (the reference Recon
        heatmap's entity-heat view; access-frequency heat would need
        audit-fed counters, size heat is the warehouse-derivable
        equivalent)."""
        vols = self.om.list_volumes()
        out = {"volumes": len(vols), "buckets": 0, "keys": 0, "bytes": 0,
               "per_volume": {}, "heat_cells": []}
        for v in vols:
            name = v["name"]
            buckets = self.om.list_buckets(name)
            vsum = {"buckets": len(buckets), "keys": 0, "bytes": 0}
            for b in buckets:
                keys = self.om.list_keys(name, b["name"])
                nbytes = int(sum(k["size"] for k in keys))
                vsum["keys"] += len(keys)
                vsum["bytes"] += nbytes
                out["heat_cells"].append({
                    "volume": name,
                    "bucket": b["name"],
                    "keys": len(keys),
                    "bytes": nbytes,
                })
            out["buckets"] += vsum["buckets"]
            out["keys"] += vsum["keys"]
            out["bytes"] += vsum["bytes"]
            out["per_volume"][name] = vsum
        out["heat_cells"].sort(key=lambda c: -c["bytes"])
        return out

    def file_size_histogram(self) -> dict:
        """Power-of-two size buckets (FileSizeCountTask analog)."""
        buckets: dict[str, int] = {}
        for v in self.om.list_volumes():
            for b in self.om.list_buckets(v["name"]):
                for k in self.om.list_keys(v["name"], b["name"]):
                    size = max(1, k["size"])
                    exp = int(math.ceil(math.log2(size)))
                    label = f"<=2^{exp}"
                    buckets[label] = buckets.get(label, 0) + 1
        return dict(sorted(buckets.items(),
                           key=lambda kv: int(kv[0].split("^")[1])))

class TableInsights:
    """OM DB insights (the reference Recon's OM DB Insights page +
    table-insight task endpoints: row counts per table, open-key and
    pending-deletion listings with ages, so an operator can spot leaked
    open keys or a stuck purge chain without touching the OM)."""

    def __init__(self, om: OzoneManager):
        self.om = om

    def table_counts(self) -> dict:
        from ozone_tpu.om.metadata import _TABLES

        return {t: self.om.store.count(t) for t in _TABLES}

    def open_keys(self, limit: int = 100) -> list[dict]:
        # collect ALL before sorting: the oldest (most interesting)
        # entry may sort last lexicographically, and a pre-sort limit
        # would hide exactly the stuck session the operator is hunting
        now = time.time()
        rows = []
        for k, info in self.om.store.iterate("open_keys"):
            rows.append({
                "key": k,
                "size": info.get("size", 0),
                "replication": info.get("replication"),
                "hsync": bool(info.get("hsync_client_id")),
                "age_s": round(now - info.get("created", now), 1),
            })
        rows.sort(key=lambda r: -r["age_s"])
        return rows[:limit]

    def deleted_keys(self, limit: int = 100) -> list[dict]:
        now = time.time()
        rows = []
        for k, info in self.om.store.iterate("deleted_keys"):
            # store key is <key>:<ts> (DeleteKey.apply)
            ts = None
            if ":" in k:
                try:
                    ts = float(k.rpartition(":")[2])
                except ValueError:
                    ts = None
            rows.append({
                "key": k,
                "size": info.get("size", 0),
                "blocks": len(info.get("block_groups", [])),
                "pending_s": (round(now - ts, 1)
                              if ts is not None else None),
            })
        rows.sort(key=lambda r: -(r["pending_s"] or 0))
        return rows[:limit]


class NSSummaryIndex:
    """Delta-fed per-directory namespace summaries (the reference's
    NSSummaryTask family: NSSummaryTaskWithFSO aggregates file count /
    bytes per directory object id from OM update batches; OBS/LEGACY
    buckets aggregate at bucket level). Serves du-style queries: direct
    totals per directory plus recursive totals down the subtree —
    without walking the namespace per request."""

    def __init__(self, om: OzoneManager):
        self.om = om
        self._txid = 0
        self.full_rebuilds = 0
        self._lock = threading.RLock()
        # FSO: (vol, bkt, object_id) -> {"files": n, "bytes": n}
        self._dir_agg: dict[tuple, dict] = {}
        # FSO structure: (vol,bkt) -> {object_id: {"name","parent_id"}}
        self._dirs: dict[tuple, dict[str, dict]] = {}
        self._children: dict[tuple, set] = {}  # (v,b,parent) -> ids
        # retirement maps: store key -> prior contribution
        self._file_at: dict[str, tuple] = {}  # -> (v,b,parent,size)
        self._dir_at: dict[str, tuple] = {}   # -> (v,b,object_id,parent)
        # OBS: (vol,bkt) -> {"files": n, "bytes": n}; key -> (v,b,size)
        self._obs_agg: dict[tuple, dict] = {}
        self._key_at: dict[str, tuple] = {}
        self._rebuild()

    # ------------------------------------------------------------ feed
    def _rebuild(self) -> None:
        with self._lock:
            for d in (self._dir_agg, self._dirs, self._children,
                      self._file_at, self._dir_at, self._obs_agg,
                      self._key_at):
                d.clear()
            self._txid = self.om.store.txid
            self.full_rebuilds += 1
            for table in ("dirs", "files", "keys"):
                for k, info in self.om.store.iterate(table):
                    self._apply(table, k, info)

    def refresh(self) -> None:
        with self._lock:
            updates, txid, complete = self.om.store.get_updates_since(
                self._txid)
            if not complete:
                self._rebuild()
                return
            for _, table, key, value in updates:
                if table in ("dirs", "files", "keys"):
                    self._apply(table, key, value)
            self._txid = txid

    @staticmethod
    def _vb(store_key: str):
        parts = store_key.split("/")
        return (parts[1], parts[2]) if len(parts) >= 3 else None

    def _apply(self, table: str, key: str, info) -> None:
        if key.startswith("/.snap"):
            return  # derived snapshot rows (journal=False)
        if table == "keys":
            if key.endswith("/"):
                return  # LEGACY directory markers are not files
            prior = self._key_at.pop(key, None)
            if prior is not None:
                v, b, sz = prior
                agg = self._obs_agg.get((v, b))
                if agg is not None:
                    agg["files"] -= 1
                    agg["bytes"] -= sz
            if info is None:
                return
            vb = self._vb(key)
            if vb is None:
                return
            sz = int(info.get("size", 0))
            agg = self._obs_agg.setdefault(vb, {"files": 0, "bytes": 0})
            agg["files"] += 1
            agg["bytes"] += sz
            self._key_at[key] = (*vb, sz)
            return
        if table == "files":
            prior = self._file_at.pop(key, None)
            if prior is not None:
                v, b, parent, sz = prior
                agg = self._dir_agg.get((v, b, parent))
                if agg is not None:
                    agg["files"] -= 1
                    agg["bytes"] -= sz
            if info is None:
                return
            vb = self._vb(key)
            if vb is None:
                return
            parent = str(info.get("parent_id", key.split("/")[3]))
            sz = int(info.get("size", 0))
            agg = self._dir_agg.setdefault(
                (*vb, parent), {"files": 0, "bytes": 0})
            agg["files"] += 1
            agg["bytes"] += sz
            self._file_at[key] = (*vb, parent, sz)
            return
        # dirs table: structural rows
        prior = self._dir_at.pop(key, None)
        if prior is not None:
            v, b, oid, parent = prior
            self._dirs.get((v, b), {}).pop(oid, None)
            self._children.get((v, b, parent), set()).discard(oid)
        if info is None:
            return
        vb = self._vb(key)
        if vb is None:
            return
        oid = str(info["object_id"])
        parent = str(info.get("parent_id", key.split("/")[3]))
        self._dirs.setdefault(vb, {})[oid] = {
            "name": info.get("name", ""), "parent_id": parent}
        self._children.setdefault((*vb, parent), set()).add(oid)
        self._dir_at[key] = (*vb, oid, parent)

    # ----------------------------------------------------------- query
    def _recursive(self, v: str, b: str, oid: str) -> dict:
        direct = self._dir_agg.get((v, b, oid), {"files": 0, "bytes": 0})
        total_f, total_b = direct["files"], direct["bytes"]
        for child in self._children.get((v, b, oid), ()):  # DFS
            sub = self._recursive(v, b, child)
            total_f += sub["total_files"]
            total_b += sub["total_bytes"]
        return {"files": direct["files"], "bytes": direct["bytes"],
                "total_files": total_f, "total_bytes": total_b}

    def du(self, path: str) -> dict:
        """du-style breakdown for /vol/bucket[/dir...]: direct and
        recursive totals plus immediate children (the reference's
        /api/v1/namespace/du)."""
        from ozone_tpu.om import fso
        from ozone_tpu.om.requests import OMError

        self.refresh()
        parts = [p for p in path.split("/") if p]
        with self._lock:
            if len(parts) < 2:
                # volume or root: bucket-level rollup
                out = {"path": path or "/", "children": []}
                tf = tb = 0
                for (v, b), agg in sorted(self._obs_agg.items()):
                    if parts and v != parts[0]:
                        continue
                    out["children"].append({
                        "path": f"/{v}/{b}",
                        "total_files": agg["files"],
                        "total_bytes": agg["bytes"]})
                    tf += agg["files"]
                    tb += agg["bytes"]
                fso_buckets = set(self._dirs) | {
                    (v, b) for (v, b, _) in self._dir_agg}
                for v, b in sorted(fso_buckets):
                    if parts and v != parts[0]:
                        continue
                    s = self._recursive(v, b, fso.ROOT_ID)
                    out["children"].append({
                        "path": f"/{v}/{b}",
                        "total_files": s["total_files"],
                        "total_bytes": s["total_bytes"]})
                    tf += s["total_files"]
                    tb += s["total_bytes"]
                out["total_files"], out["total_bytes"] = tf, tb
                return out
            v, b, rest = parts[0], parts[1], "/".join(parts[2:])
            from ozone_tpu.om.metadata import bucket_key

            if not self.om.store.exists("buckets", bucket_key(v, b)):
                raise KeyError(path)  # typo must not read as "empty"
            if (v, b) in self._obs_agg and not rest:
                agg = self._obs_agg[(v, b)]
                return {"path": f"/{v}/{b}", "children": [],
                        "files": agg["files"], "bytes": agg["bytes"],
                        "total_files": agg["files"],
                        "total_bytes": agg["bytes"]}
            # FSO: resolve the path to a directory object id
            oid = fso.ROOT_ID
            if rest:
                try:
                    parent, missing = fso.resolve(self.om.store, v, b,
                                                  rest)
                except OMError:
                    missing = [rest]
                    parent = None
                if missing or parent is None:
                    raise KeyError(path)
                oid = parent
            out = {"path": f"/{v}/{b}" + (f"/{rest}" if rest else ""),
                   **self._recursive(v, b, oid), "children": []}
            for child in sorted(self._children.get((v, b, oid), ())):
                d = self._dirs.get((v, b), {}).get(child, {})
                s = self._recursive(v, b, child)
                out["children"].append({
                    "path": out["path"] + "/" + d.get("name", child),
                    "total_files": s["total_files"],
                    "total_bytes": s["total_bytes"]})
            return out


class ContainerKeyIndex:
    """Incrementally-maintained container -> keys index fed by OM WAL
    deltas (the reference's OMDBUpdatesHandler + ContainerKeyMapperTask:
    Recon tails OM RocksDB update batches and applies them to its own
    rocksdb copy instead of rescanning the namespace)."""

    def __init__(self, om: OzoneManager):
        self.om = om
        # cid -> {store_key: table}; FSO store keys are resolved to real
        # namespace paths at query time (they embed parent object ids)
        self._index: dict[int, dict[str, str]] = {}
        self._key_containers: dict[str, list[int]] = {}
        self._txid = 0
        self.full_rebuilds = 0
        self._lock = threading.RLock()
        self._rebuild()

    def _rebuild(self) -> None:
        with self._lock:
            self._index.clear()
            self._key_containers.clear()
            self._txid = self.om.store.txid
            self.full_rebuilds += 1
            for table in ("keys", "files"):
                for k, info in self.om.store.iterate(table):
                    self._apply(table, k, info)

    @staticmethod
    def _derived(key: str) -> bool:
        """Materialized snapshot rows are DERIVED state: they duplicate
        live keys under /.snapshot/ and are written with journal=False
        (the WAL delta deliberately omits them), so indexing them on a
        rebuild would leave entries the delta path can never retire."""
        return key.startswith("/.snap")

    def _apply(self, table: str, key: str, info) -> None:
        if self._derived(key):
            return
        # drop the previous mapping for this key path, then re-add
        for cid in self._key_containers.pop(key, []):
            m = self._index.get(cid)
            if m is not None:
                m.pop(key, None)
                if not m:
                    del self._index[cid]
        if info is None:
            return
        cids = []
        for g in info.get("block_groups", []):
            cid = int(g["container_id"])
            self._index.setdefault(cid, {})[key] = table
            cids.append(cid)
        if cids:
            self._key_containers[key] = cids

    def refresh(self) -> None:
        with self._lock:
            updates, txid, complete = self.om.store.get_updates_since(
                self._txid
            )
            if not complete:
                self._rebuild()
                return
            for utx, table, key, value in updates:
                if table in ("keys", "files"):
                    self._apply(table, key, value)
            self._txid = txid

    def _display_path(self, store_key: str, table: str) -> str:
        """Real namespace path for a store key: keys-table keys ARE paths;
        files-table keys are /vol/bucket/<parentId>/<name> and resolve by
        walking the dir_ids index upward (fso.py id_key layout)."""
        if table != "files":
            return store_key
        from ozone_tpu.om.fso import ROOT_ID

        parts = store_key.split("/")
        if len(parts) < 5:
            return store_key
        vol, bkt, pid = parts[1], parts[2], parts[3]
        segs = ["/".join(parts[4:])]
        store = self.om.store
        while pid != ROOT_ID:
            row = store.get("dir_ids", f"/{vol}/{bkt}/{pid}")
            if row is None:
                break  # detached subtree pending purge
            segs.append(row["name"])
            pid = row["parent_id"]
        return f"/{vol}/{bkt}/" + "/".join(reversed(segs))

    def container_key_map(self) -> dict[int, list[str]]:
        self.refresh()
        with self._lock:
            snapshot = {
                cid: dict(m) for cid, m in self._index.items()
            }
        return {
            cid: sorted(
                self._display_path(k, table) for k, table in m.items()
            )
            for cid, m in snapshot.items()
        }


class ReconWarehouse:
    """Persistent stats warehouse (the reference's jOOQ/Derby SQL
    warehouse: GlobalStats / FileCountBySize / cluster-growth tables,
    schema generated in recon-codegen). Sqlite: one `stats` table of
    timestamped JSON task outputs queryable by kind."""

    def __init__(self, path):
        import sqlite3
        from pathlib import Path

        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(str(p), check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS stats "
            "(id INTEGER PRIMARY KEY AUTOINCREMENT, ts REAL, kind TEXT, "
            "data TEXT)"
        )
        self._conn.execute(
            "CREATE INDEX IF NOT EXISTS stats_kind ON stats (kind, ts)"
        )
        self._conn.commit()
        self._lock = threading.Lock()

    def record(self, kind: str, data: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO stats (ts, kind, data) VALUES (?, ?, ?)",
                (time.time(), kind, json.dumps(data, default=str)),
            )
            self._conn.commit()

    def history(self, kind: str, limit: int = 100) -> list[dict]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT ts, data FROM stats WHERE kind=? "
                "ORDER BY ts DESC LIMIT ?",
                (kind, limit),
            ).fetchall()
        return [
            {"ts": ts, **json.loads(data)} for ts, data in rows
        ]

    def latest(self, kind: str):
        h = self.history(kind, limit=1)
        return h[0] if h else None

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class ReconScmView:
    """Passive SCM health view (ReconStorageContainerManagerFacade +
    fsck/ container health task analog)."""

    def __init__(self, scm: StorageContainerManager):
        self.scm = scm

    def container_health(self) -> dict:
        missing, under, over, healthy = [], [], [], []
        for c in self.scm.containers.containers():
            if c.state in (ContainerState.DELETED, ContainerState.OPEN):
                continue
            if c.replication.type is ReplicationType.EC:
                count = ECReplicaCount(c, self.scm.nodes)
                if not count.recoverable:
                    missing.append(c.id)
                elif count.missing_indexes:
                    under.append(c.id)
                elif count.excess_indexes:
                    over.append(c.id)
                else:
                    healthy.append(c.id)
            else:
                live = len(c.replicas)
                if live == 0:
                    missing.append(c.id)
                elif live < c.replication.factor:
                    under.append(c.id)
                elif live > c.replication.factor:
                    over.append(c.id)
                else:
                    healthy.append(c.id)
        return {
            "healthy": healthy,
            "under_replicated": under,
            "over_replicated": over,
            "missing": missing,
        }

    def _rack_of(self) -> dict:
        return {n.dn_id: n.rack for n in self.scm.nodes.nodes()}

    def unhealthy_containers(self,
                             state: Optional[str] = None) -> list[dict]:
        """Per-container detail for every unhealthy container
        (reference: /api/v1/containers/unhealthy/{state} from the
        ContainerHealthTask's UnhealthyContainers table): replica
        placement, missing/excess indexes, and rack-spread
        mis-replication. `state` filters to MISSING / UNDER_REPLICATED /
        OVER_REPLICATED / MIS_REPLICATED."""
        from ozone_tpu.scm.placement import RackScatterPlacement

        racks = self._rack_of()
        total_racks = len(set(racks.values())) or 1
        out = []
        for c in self.scm.containers.containers():
            if c.state in (ContainerState.DELETED, ContainerState.OPEN):
                continue
            replicas = [
                {"dn": dn,
                 "index": getattr(r, "replica_index", None),
                 "rack": racks.get(dn)}
                for dn, r in sorted(c.replicas.items())
            ]
            states = []
            detail: dict = {}
            if c.replication.type is ReplicationType.EC:
                count = ECReplicaCount(c, self.scm.nodes)
                expected = c.replication.ec.all_units
                if count.missing_indexes and not count.recoverable:
                    states.append("MISSING")
                elif count.missing_indexes:
                    states.append("UNDER_REPLICATED")
                if count.excess_indexes:
                    states.append("OVER_REPLICATED")
                detail = {
                    "missing_indexes": sorted(count.missing_indexes),
                    "excess_indexes": sorted(count.excess_indexes),
                }
            else:
                expected = c.replication.factor
                live = len(c.replicas)
                if live == 0:
                    states.append("MISSING")
                elif live < expected:
                    states.append("UNDER_REPLICATED")
                elif live > expected:
                    states.append("OVER_REPLICATED")
            racks_used = len({r["rack"] for r in replicas
                              if r["rack"] is not None})
            if replicas and not RackScatterPlacement.validate(
                    racks_used, total_racks, expected):
                states.append("MIS_REPLICATED")
            if not states:
                continue
            if state is not None and state.upper() not in states:
                continue
            out.append({
                "container": c.id,
                "states": states,
                "replication": str(c.replication),
                "expected": expected,
                "actual": len(replicas),
                "racks_used": racks_used,
                "racks_expected": min(expected, total_racks),
                "replicas": replicas,
                **detail,
            })
        return out

    def pipeline_table(self) -> list[dict]:
        return [
            {
                "id": p.id,
                "replication": str(p.replication),
                "state": p.state.value,
                "nodes": list(p.nodes),
            }
            for p in self.scm.containers.pipelines()
        ]

    def node_table(self) -> list[dict]:
        return [
            {
                "dn_id": n.dn_id,
                "rack": n.rack,
                "state": n.state.value,
                "op_state": n.op_state.value,
                "capacity_bytes": n.capacity_bytes,
                "layout_version": n.layout_version,
                "used_bytes": n.used_bytes,
                "utilization": (
                    n.used_bytes / n.capacity_bytes if n.capacity_bytes else 0
                ),
            }
            for n in self.scm.nodes.nodes()
        ]


class ReconServer:
    """Recon REST API over the service HTTP server."""

    def __init__(self, om: OzoneManager, scm: StorageContainerManager,
                 host: str = "127.0.0.1", port: int = 0, db_path=None,
                 scan_cache_ttl_s: float = 15.0):
        self.tasks = ReconTasks(om)
        self.scm_view = ReconScmView(scm)
        self.key_index = ContainerKeyIndex(om)
        self.nssummary = NSSummaryIndex(om)
        self.insights = TableInsights(om)
        self.warehouse = (
            ReconWarehouse(db_path) if db_path is not None else None
        )
        #: optional cluster TraceCollector (daemons wire theirs in) —
        #: the slow-traces view then merges its flight recorder with
        #: the process-local one
        self.trace_collector = None
        # full-namespace-scan task outputs are served from a short TTL
        # cache: the UI polls every 10s from any number of tabs, and a
        # scan must cost at most one pass per TTL window, not one per
        # request (the reference serves these from the warehouse tables
        # its ReconTaskController refreshed, never by scanning inline)
        self._scan_cache_ttl = scan_cache_ttl_s
        self._scan_cache: dict[str, tuple[float, object]] = {}
        self._scan_lock = threading.Lock()
        from ozone_tpu.utils.http_server import ServiceHttpServer

        self._base = ServiceHttpServer(
            "recon", host, port, status_provider=self.api_summary
        )
        # extend the handler routing with /api endpoints
        orig_handler = self._base._httpd.RequestHandlerClass
        recon = self

        class Handler(orig_handler):
            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                u = urlparse(self.path)
                path, q = u.path, parse_qs(u.query)
                if path == "/api/nssummary":
                    try:
                        out = recon.nssummary.du(
                            q.get("path", ["/"])[0])
                    except KeyError as e:
                        self._send(404, json.dumps(
                            {"error": f"no such path {e}"}))
                        return
                    self._send(200, json.dumps(out, indent=2,
                                               default=str))
                    return
                if path == "/api/containers/unhealthy":
                    out = recon.scm_view.unhealthy_containers(
                        q.get("state", [None])[0])
                    self._send(200, json.dumps(out, indent=2,
                                               default=str))
                    return
                if path in ("/", "/ui"):
                    from ozone_tpu.recon.ui import RECON_INDEX_HTML

                    self._send(200, RECON_INDEX_HTML,
                               "text/html; charset=utf-8")
                    return
                routes = {
                    "/api/namespace": lambda: recon._scan(
                        "namespace", recon.tasks.namespace_summary),
                    "/api/filesizes": lambda: recon._scan(
                        "filesizes", recon.tasks.file_size_histogram),
                    # ?id=<cid> narrows to one container (the
                    # reference's per-container key endpoint)
                    "/api/containers/keys": lambda: {
                        str(k): v
                        for k, v in recon.key_index.container_key_map()
                        .items()
                        if not q.get("id")
                        or str(k) == q["id"][0]
                    },
                    # derived from the (cached, warehouse-recorded)
                    # namespace scan: no extra OM walk in the request path
                    "/api/heatmap": lambda: {
                        "cells": recon._scan(
                            "namespace", recon.tasks.namespace_summary
                        ).get("heat_cells", [])
                    },
                    "/api/containers/health": recon.scm_view.container_health,
                    "/api/nodes": recon.scm_view.node_table,
                    "/api/pipelines": recon.scm_view.pipeline_table,
                    "/api/summary": recon.api_summary,
                    "/api/insights/tables": lambda: recon._scan(
                        "table_counts", recon.insights.table_counts),
                    "/api/insights/open_keys":
                        recon.insights.open_keys,
                    "/api/insights/deleted_keys":
                        recon.insights.deleted_keys,
                    # lifecycle sweeper panel: fencing term, cursor,
                    # last-sweep stats + live tiering counters
                    "/api/lifecycle": recon.lifecycle_view,
                    # geo-replication panel: shipper term/cursor,
                    # per-bucket rules, and WAL-head lag gauges
                    # (entries + seconds behind)
                    "/api/replication": recon.replication_view,
                    # shared codec service: batch fill ratio, queue
                    # depth, coalescing + QoS counters (the device's
                    # continuous-batching health, next to lifecycle —
                    # its main bulk consumer)
                    "/api/codec": recon.codec_view,
                    # persistent mesh executor: multi-chip dispatch,
                    # coalescing and spill accounting (the fleet
                    # reconstruction/bulk-tiering datapath's health)
                    "/api/mesh": recon.mesh_view,
                    # admission-control panel: per-hop controller
                    # knobs/in-flight plus every rejection counter
                    "/api/admission": recon.admission_view,
                    # small-object fast path: inline/needle counters,
                    # live slab census (count, dead-byte ratio) and
                    # threshold knob echo
                    "/api/smallobj": recon.smallobj_view,
                    # sharded metadata plane: this OM's shard config,
                    # the root shard map (when this OM hosts it), and
                    # the routing / 2PC / follower-read counters
                    "/api/shards": recon.shard_view,
                    # slow-request flight recorder: retained
                    # over-SLO traces; ?id=<traceId> returns the full
                    # span set + critical path for one trace
                    "/api/traces/slow": lambda: recon.traces_slow_view(
                        q.get("id", [None])[0],
                        int(q.get("limit", ["50"])[0])),
                }
                fn = routes.get(path)
                if fn is not None:
                    self._send(200, json.dumps(fn(), indent=2, default=str))
                elif path.startswith("/api/history/"):
                    if recon.warehouse is None:
                        self._send(404, '{"error": "no warehouse"}')
                        return
                    kind = path.rpartition("/")[2]
                    self._send(
                        200,
                        json.dumps(recon.warehouse.history(kind),
                                   indent=2, default=str),
                    )
                else:
                    super().do_GET()

        self._base._httpd.RequestHandlerClass = Handler

    def _scan(self, key: str, fn):
        """Run a namespace-scan task at most once per TTL window; callers
        in between get the cached output."""
        now = time.monotonic()
        with self._scan_lock:
            hit = self._scan_cache.get(key)
            if hit is not None and now - hit[0] < self._scan_cache_ttl:
                return hit[1]
        val = fn()
        with self._scan_lock:
            self._scan_cache[key] = (time.monotonic(), val)
        return val

    def traces_slow_view(self, trace_id: Optional[str] = None,
                         limit: int = 50) -> dict:
        """Slow-request flight recorder surface: newest-first summaries
        of traces retained past their per-op SLO, or — with ?id= — one
        trace's full span set and critical path. PEEKS at the
        process-local recorder (plus the daemon's TraceCollector ring
        when one is wired in); a monitoring GET never starts tracing."""
        from ozone_tpu.utils.tracing import Tracer

        recorders = [Tracer.instance().recorder]
        if self.trace_collector is not None:
            recorders.append(self.trace_collector.recorder)
        if trace_id:
            for r in recorders:
                entry = r.trace(trace_id)
                if entry is not None:
                    return entry
            return {"error": f"trace {trace_id} not retained"}
        out, seen = [], set()
        for r in recorders:
            for e in r.slow(limit):
                if e["traceId"] not in seen:
                    seen.add(e["traceId"])
                    out.append(e)
        out.sort(key=lambda e: e["start"], reverse=True)
        return {"traces": out[:limit]}

    def codec_view(self) -> dict:
        """Shared codec service snapshot for the dashboard panel:
        fill/coalescing ratios derived from the counters plus live
        queue depth and knob echo (codec/service.stats). PEEKS at the
        singleton — a monitoring GET must never be the thing that
        spawns the device-owning dispatcher in a process that does no
        codec work."""
        from ozone_tpu.codec import service as codec_service

        if not codec_service.enabled():
            return {"enabled": False}
        svc = codec_service._service
        if svc is None or not svc._running:
            return {"enabled": True, "started": False}
        return svc.stats()

    def mesh_view(self) -> dict:
        """Persistent mesh executor snapshot for the dashboard panel:
        dispatch/fill/coalescing accounting, in-flight depth, program
        census (device vs host-twin) and spill knob echo
        (parallel/mesh_executor.stats). PEEKS at the singleton exactly
        like codec_view — a monitoring GET must never be the thing that
        spawns the mesh-owning dispatcher (or builds a mesh) in a
        process that does no mesh work."""
        from ozone_tpu.parallel import mesh_executor

        if not mesh_executor.enabled():
            return {"enabled": False}
        ex = mesh_executor._executor
        if ex is None or not ex._running:
            return {"enabled": True, "started": False,
                    "spill_enabled": mesh_executor.spill_enabled(),
                    "spill_watermark": mesh_executor.spill_watermark()}
        return ex.stats()

    def admission_view(self) -> dict:
        """Overload-protection snapshot for the dashboard panel: every
        installed hop controller (knob echo, live in-flight depth,
        tenants seen, SLO shed state) plus the full ``admission``
        counter family — per-hop, per-reason rejection counts, so an
        operator can tell SHED (rejections climbing, goodput flat)
        from COLLAPSE (everything falling together). PEEKS at the
        controller cache — a monitoring GET must never be the thing
        that installs an admission controller."""
        from ozone_tpu import admission
        from ozone_tpu.utils.metrics import registry

        hops = {hop: ctl.snapshot()
                for hop, ctl in admission.controllers().items()}
        return {
            "enabled": any(s["enabled"] for s in hops.values()),
            "hops": hops,
            "counters": registry("admission").snapshot(),
        }

    def smallobj_view(self) -> dict:
        """Small-object fast-path snapshot for the dashboard panel: the
        ``smallobj`` counter family (inline hits, needles packed, slabs
        flushed, compaction bytes), a live slab census aggregated from
        the OM's slab rows (count, live/dead bytes, worst dead ratio —
        the compaction sweeper's backlog signal) and the threshold/knob
        echo. PEEKS at store rows and the shared registry only."""
        from ozone_tpu.utils.config import env_float, env_int
        from ozone_tpu.utils.metrics import registry

        store = self.tasks.om.store
        slabs = live = dead = 0
        worst = 0.0
        for _, srow in store.iterate("slabs"):
            slabs += 1
            n = int(srow.get("length", 0))
            d = int(srow.get("dead_bytes", 0))
            live += n - d
            dead += d
            if n:
                worst = max(worst, d / n)
        return {
            "counters": registry("smallobj").snapshot(),
            "slabs": {"count": slabs, "live_bytes": live,
                      "dead_bytes": dead,
                      "worst_dead_ratio": round(worst, 3)},
            "knobs": {
                "inline_max": env_int("OZONE_TPU_INLINE_MAX", 4096),
                "needle_max": env_int("OZONE_TPU_NEEDLE_MAX",
                                      256 * 1024),
                "slab_target_mib": env_float(
                    "OZONE_TPU_SLAB_TARGET_MIB", 4.0),
                "slab_linger_ms": env_float(
                    "OZONE_TPU_SLAB_LINGER_MS", 8.0),
                "dead_ratio": env_float(
                    "OZONE_TPU_SLAB_DEAD_RATIO", 0.5),
            },
        }

    def shard_view(self) -> dict:
        """Sharded metadata plane snapshot for the dashboard panel: the
        local OM's replicated `system/shard_config` row (which slots
        this ring owns, at which epoch), the root shard map when this
        OM hosts it, and the om.shard counter family (routes, moved
        rejections, cross-shard 2PC outcomes, follower-read hit/miss,
        lease renewals). PEEKS at store rows and the shared registry —
        a monitoring GET never installs or mutates shard state."""
        from ozone_tpu.utils.metrics import registry

        store = self.tasks.om.store
        cfg = store.get("system", "shard_config")
        mj = store.get("system", "shard_map")
        out: dict = {"sharded": cfg is not None or mj is not None,
                     "counters": registry("om.shard").snapshot()}
        if cfg is not None:
            out["config"] = {"epoch": cfg["epoch"],
                             "shard_id": cfg["shard_id"],
                             "slot_count": cfg["slot_count"],
                             "owned_slots": len(cfg["owned"])}
        if mj is not None:
            counts: dict[str, int] = {}
            for idx in mj["slots"]:
                sid = mj["shards"][idx]
                counts[sid] = counts.get(sid, 0) + 1
            out["map"] = {"epoch": mj["epoch"],
                          "slot_count": len(mj["slots"]),
                          "slots_per_shard": counts,
                          "addresses": dict(mj.get("addresses") or {})}
        return out

    def replication_view(self) -> dict:
        """Geo-replication shipper status + per-bucket rule census for
        the dashboard panel: fencing term, WAL cursor, live counters,
        and the lag gauges (journal entries and seconds behind the WAL
        head) operators alarm on."""
        om = self.tasks.om
        out = om.geo_status()
        if "lag" not in out:
            # no shipper installed on this process (e.g. a follower):
            # derive the lag from a throwaway shipper over the same
            # store — a monitoring GET must still report how far
            # behind the cluster is
            from ozone_tpu.replication_geo.shipper import (
                ReplicationShipper,
            )

            out["lag"] = ReplicationShipper(om).lag()
        buckets = []
        for bk, brow in om.store.iterate("buckets"):
            rules = brow.get("geo_replication") or []
            if rules:
                buckets.append({"bucket": bk, "rules": rules})
        out["buckets"] = buckets
        return out

    def lifecycle_view(self) -> dict:
        """Lifecycle sweeper status + per-bucket rule census for the
        dashboard panel (tiering is the main background consumer of
        device cycles, so operators watch it next to container
        health)."""
        out = self.tasks.om.lifecycle_status()
        buckets = []
        for bk, brow in self.tasks.om.store.iterate("buckets"):
            rules = brow.get("lifecycle") or []
            if rules:
                buckets.append({"bucket": bk, "rules": rules})
        out["buckets"] = buckets
        return out

    def api_summary(self) -> dict:
        health = self.scm_view.container_health()
        return {
            "ts": time.time(),
            "namespace": self._scan("namespace",
                                    self.tasks.namespace_summary),
            "containers": {k: len(v) for k, v in health.items()},
            "nodes": self.scm_view.node_table(),
        }

    def run_tasks_once(self) -> None:
        """One warehouse tick (ReconTaskController analog): refresh the
        delta-fed index and persist every task's output with a
        timestamp so operators get history, not just now. Runs the scans
        fresh and primes the serving cache with the results."""
        self.key_index.refresh()
        self.nssummary.refresh()
        ns = self.tasks.namespace_summary()
        sizes = self.tasks.file_size_histogram()
        with self._scan_lock:
            now = time.monotonic()
            self._scan_cache["namespace"] = (now, ns)
            self._scan_cache["filesizes"] = (now, sizes)
        if self.warehouse is None:
            return
        self.warehouse.record("namespace", ns)
        self.warehouse.record("filesizes", {"buckets": sizes})
        health = self.scm_view.container_health()
        self.warehouse.record(
            "container_health", {k: len(v) for k, v in health.items()}
        )
        self.warehouse.record("nodes", {"nodes": self.scm_view.node_table()})
        self.warehouse.record("table_counts", self.insights.table_counts())

    @property
    def address(self) -> str:
        return self._base.address

    def start(self) -> None:
        self._base.start()

    def stop(self) -> None:
        self._base.stop()
        if self.warehouse is not None:
            self.warehouse.close()
