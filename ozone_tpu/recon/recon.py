"""Recon: cluster observability warehouse + REST API.

Mirror of the reference's Recon service (hadoop-ozone/recon ReconServer:
an OM-metadata follower feeding aggregation tasks — ContainerKeyMapperTask,
FileSizeCountTask, NSSummaryTask — plus a passive SCM view detecting
missing/under-replicated containers, exposed over REST for operators and
the UI). Here: tasks run over a snapshot/tail of the OM store and the SCM
object's live state, materializing

  - namespace summary (volumes/buckets/keys, bytes)
  - file-size histogram (FileSizeCountTask analog)
  - container -> key mapping (ContainerKeyMapperTask analog)
  - container health: missing / under- / over-replicated (fsck view)
  - node utilization table

served as JSON endpoints on the service HTTP server (/api/...).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Optional

from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.pipeline import ReplicationType
from ozone_tpu.scm.replication_manager import ECReplicaCount
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.storage.ids import ContainerState


class ReconTasks:
    """Aggregation tasks over OM metadata (ReconOmTask pipeline analog)."""

    def __init__(self, om: OzoneManager):
        self.om = om

    def namespace_summary(self) -> dict:
        vols = self.om.list_volumes()
        out = {"volumes": len(vols), "buckets": 0, "keys": 0, "bytes": 0,
               "per_volume": {}}
        for v in vols:
            name = v["name"]
            buckets = self.om.list_buckets(name)
            vsum = {"buckets": len(buckets), "keys": 0, "bytes": 0}
            for b in buckets:
                keys = self.om.list_keys(name, b["name"])
                vsum["keys"] += len(keys)
                vsum["bytes"] += sum(k["size"] for k in keys)
            out["buckets"] += vsum["buckets"]
            out["keys"] += vsum["keys"]
            out["bytes"] += vsum["bytes"]
            out["per_volume"][name] = vsum
        return out

    def file_size_histogram(self) -> dict:
        """Power-of-two size buckets (FileSizeCountTask analog)."""
        buckets: dict[str, int] = {}
        for v in self.om.list_volumes():
            for b in self.om.list_buckets(v["name"]):
                for k in self.om.list_keys(v["name"], b["name"]):
                    size = max(1, k["size"])
                    exp = int(math.ceil(math.log2(size)))
                    label = f"<=2^{exp}"
                    buckets[label] = buckets.get(label, 0) + 1
        return dict(sorted(buckets.items(),
                           key=lambda kv: int(kv[0].split("^")[1])))

    def container_key_map(self) -> dict[int, list[str]]:
        """container id -> keys with data in it (ContainerKeyMapperTask)."""
        out: dict[int, list[str]] = {}
        for v in self.om.list_volumes():
            for b in self.om.list_buckets(v["name"]):
                for k in self.om.list_keys(v["name"], b["name"]):
                    path = f"/{v['name']}/{b['name']}/{k['name']}"
                    for g in k.get("block_groups", []):
                        out.setdefault(g["container_id"], []).append(path)
        return out


class ReconScmView:
    """Passive SCM health view (ReconStorageContainerManagerFacade +
    fsck/ container health task analog)."""

    def __init__(self, scm: StorageContainerManager):
        self.scm = scm

    def container_health(self) -> dict:
        missing, under, over, healthy = [], [], [], []
        for c in self.scm.containers.containers():
            if c.state in (ContainerState.DELETED, ContainerState.OPEN):
                continue
            if c.replication.type is ReplicationType.EC:
                count = ECReplicaCount(c, self.scm.nodes)
                if not count.recoverable:
                    missing.append(c.id)
                elif count.missing_indexes:
                    under.append(c.id)
                elif count.excess_indexes:
                    over.append(c.id)
                else:
                    healthy.append(c.id)
            else:
                live = len(c.replicas)
                if live == 0:
                    missing.append(c.id)
                elif live < c.replication.factor:
                    under.append(c.id)
                elif live > c.replication.factor:
                    over.append(c.id)
                else:
                    healthy.append(c.id)
        return {
            "healthy": healthy,
            "under_replicated": under,
            "over_replicated": over,
            "missing": missing,
        }

    def node_table(self) -> list[dict]:
        return [
            {
                "dn_id": n.dn_id,
                "rack": n.rack,
                "state": n.state.value,
                "op_state": n.op_state.value,
                "capacity_bytes": n.capacity_bytes,
                "used_bytes": n.used_bytes,
                "utilization": (
                    n.used_bytes / n.capacity_bytes if n.capacity_bytes else 0
                ),
            }
            for n in self.scm.nodes.nodes()
        ]


class ReconServer:
    """Recon REST API over the service HTTP server."""

    def __init__(self, om: OzoneManager, scm: StorageContainerManager,
                 host: str = "127.0.0.1", port: int = 0):
        self.tasks = ReconTasks(om)
        self.scm_view = ReconScmView(scm)
        from ozone_tpu.utils.http_server import ServiceHttpServer

        self._base = ServiceHttpServer(
            "recon", host, port, status_provider=self.api_summary
        )
        # extend the handler routing with /api endpoints
        orig_handler = self._base._httpd.RequestHandlerClass
        recon = self

        class Handler(orig_handler):
            def do_GET(self):
                routes = {
                    "/api/namespace": recon.tasks.namespace_summary,
                    "/api/filesizes": recon.tasks.file_size_histogram,
                    "/api/containers/keys": lambda: {
                        str(k): v
                        for k, v in recon.tasks.container_key_map().items()
                    },
                    "/api/containers/health": recon.scm_view.container_health,
                    "/api/nodes": recon.scm_view.node_table,
                    "/api/summary": recon.api_summary,
                }
                fn = routes.get(self.path.split("?")[0])
                if fn is not None:
                    self._send(200, json.dumps(fn(), indent=2, default=str))
                else:
                    super().do_GET()

        self._base._httpd.RequestHandlerClass = Handler

    def api_summary(self) -> dict:
        health = self.scm_view.container_health()
        return {
            "ts": time.time(),
            "namespace": self.tasks.namespace_summary(),
            "containers": {k: len(v) for k, v in health.items()},
            "nodes": self.scm_view.node_table(),
        }

    @property
    def address(self) -> str:
        return self._base.address

    def start(self) -> None:
        self._base.start()

    def stop(self) -> None:
        self._base.stop()
