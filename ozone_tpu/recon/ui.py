"""Recon web UI: a single static dashboard page over the REST API.

Role analog of the reference's bundled React UI (hadoop-ozone/recon
`webapps/recon` — overview cards, datanode table, container health); this
build serves one dependency-free HTML page from the Recon server itself,
rendering /api/summary + /api/filesizes + /api/history. Visual rules
follow the dataviz method: headline numbers are stat tiles (not charts),
node/container state uses the reserved status palette with an icon+label
(never color alone), the single file-size series is one hue with direct
labels and no legend, and light/dark are both selected palettes swapped
via CSS custom properties.
"""

RECON_INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<meta name="viewport" content="width=device-width, initial-scale=1">
<title>Recon &mdash; ozone-tpu</title>
<style>
  .viz-root {
    color-scheme: light;
    --surface-1: #fcfcfb;
    --surface-2: #f1f0ee;
    --text-primary: #0b0b0b;
    --text-secondary: #52514e;
    --series-1: #2a78d6;
    --status-good: #0ca30c;
    --status-warning: #fab219;
    --status-critical: #d03b3b;
    --border: #d8d7d3;
  }
  @media (prefers-color-scheme: dark) {
    :root:where(:not([data-theme="light"])) .viz-root {
      color-scheme: dark;
      --surface-1: #1a1a19;
      --surface-2: #242422;
      --text-primary: #ffffff;
      --text-secondary: #c3c2b7;
      --series-1: #3987e5;
      --border: #3a3937;
    }
  }
  :root[data-theme="dark"] .viz-root {
    color-scheme: dark;
    --surface-1: #1a1a19;
    --surface-2: #242422;
    --text-primary: #ffffff;
    --text-secondary: #c3c2b7;
    --series-1: #3987e5;
    --border: #3a3937;
  }
  body { margin: 0; }
  .viz-root {
    font: 14px/1.45 system-ui, sans-serif;
    background: var(--surface-1);
    color: var(--text-primary);
    min-height: 100vh;
    padding: 24px;
    box-sizing: border-box;
  }
  h1 { font-size: 18px; margin: 0 0 4px; }
  .sub { color: var(--text-secondary); margin-bottom: 20px; }
  h2 { font-size: 14px; margin: 28px 0 10px; }
  .tiles { display: flex; flex-wrap: wrap; gap: 12px; }
  .tile {
    background: var(--surface-2);
    border: 1px solid var(--border);
    border-radius: 8px;
    padding: 12px 18px;
    min-width: 120px;
  }
  .tile .v { font-size: 26px; font-weight: 600; }
  .tile .k { color: var(--text-secondary); font-size: 12px; }
  table { border-collapse: collapse; width: 100%; max-width: 880px; }
  th, td {
    text-align: left;
    padding: 6px 10px;
    border-bottom: 1px solid var(--border);
  }
  th { color: var(--text-secondary); font-weight: 500; font-size: 12px; }
  .badge {
    display: inline-flex;
    align-items: center;
    gap: 6px;
    font-size: 12px;
  }
  .dot { width: 8px; height: 8px; border-radius: 50%; }
  .bar-row { display: flex; align-items: center; gap: 8px; margin: 3px 0; }
  .bar-label {
    width: 110px;
    text-align: right;
    color: var(--text-secondary);
    font-size: 12px;
  }
  .bar {
    height: 14px;
    background: var(--series-1);
    border-radius: 0 4px 4px 0;
    min-width: 2px;
  }
  .bar-val { font-size: 12px; }
  .heat-grid {
    display: flex; flex-wrap: wrap; gap: 6px; max-width: 720px;
  }
  .heat-cell {
    border: 1px solid var(--border); border-radius: 6px;
    padding: 8px 10px; min-width: 120px;
    /* sequential single-hue scale via opacity over the series color;
       the text label carries the value, color is reinforcement only */
    position: relative; overflow: hidden;
  }
  .heat-fill {
    position: absolute; inset: 0; background: var(--series-1);
  }
  .heat-cell .lbl, .heat-cell .val { position: relative; }
  .heat-cell .lbl { font-size: 12px; color: var(--text-secondary); }
  .heat-cell .val { font-weight: 600; }
  .err { color: var(--status-critical); }
</style>
</head>
<body>
<div class="viz-root">
  <h1>Recon &mdash; ozone-tpu cluster observability</h1>
  <div class="sub" id="ts">loading&hellip;</div>

  <div class="tiles" id="tiles"></div>

  <h2>Datanodes</h2>
  <table id="nodes">
    <thead><tr><th>node</th><th>rack</th><th>state</th><th>op state</th>
      <th>used / capacity</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Pipelines</h2>
  <table id="pipelines">
    <thead><tr><th>id</th><th>replication</th><th>state</th>
      <th>members</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Container health</h2>
  <table id="health">
    <thead><tr><th>class</th><th>count</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Namespace heat</h2>
  <div class="sub">bytes per bucket &mdash; darker is larger; each cell
    carries its own value</div>
  <div id="heat"></div>

  <h2>File sizes</h2>
  <div id="sizes"></div>
  <details><summary>table view</summary>
    <table id="sizes-table">
      <thead><tr><th>bucket</th><th>files</th></tr></thead>
      <tbody></tbody>
    </table>
  </details>

  <h2>Namespace du</h2>
  <div class="sub">recursive totals from the delta-fed NSSummary index;
    click a row to drill in</div>
  <div class="sub" id="du-path"></div>
  <table id="du">
    <thead><tr><th>path</th><th>total files</th><th>total bytes</th>
    </tr></thead>
    <tbody></tbody>
  </table>

  <h2>Growth</h2>
  <div class="sub">namespace keys and bytes over the warehouse history
    (newest right); the labels carry the current values</div>
  <div id="trend"></div>

  <h2>OM table insights</h2>
  <div class="tiles" id="insight-tiles"></div>
  <details><summary>open keys (oldest first)</summary>
    <table id="open-keys">
      <thead><tr><th>key</th><th>age (s)</th><th>hsync</th></tr></thead>
      <tbody></tbody>
    </table>
  </details>
  <details><summary>pending deletions (purge chain)</summary>
    <table id="deleted-keys">
      <thead><tr><th>entry</th><th>size</th><th>blocks</th>
        <th>pending (s)</th></tr></thead>
      <tbody></tbody>
    </table>
  </details>

  <h2>Lifecycle tiering</h2>
  <div class="sub">hot&rarr;warm sweeper (replicated&rarr;EC on device
    + TTL expiry): fencing term, sweep cursor, and live counters</div>
  <div class="tiles" id="lifecycle-tiles"></div>
  <table id="lifecycle-rules">
    <thead><tr><th>bucket</th><th>rule</th><th>prefix</th>
      <th>age (days)</th><th>action</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Geo replication</h2>
  <div class="sub">cross-cluster async bucket replication (geo-DR):
    term-fenced WAL shipper &mdash; lag behind the metadata WAL head,
    shipped/conflict counters, per-bucket rules</div>
  <div class="tiles" id="geo-tiles"></div>
  <table id="geo-rules">
    <thead><tr><th>bucket</th><th>rule</th><th>prefix</th>
      <th>destination</th><th>scheme</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Codec service</h2>
  <div class="sub">cross-request continuous batching: stripes from
    concurrent operations coalesced into shared fused device
    dispatches &mdash; fill ratio, queue depth, QoS/linger flushes</div>
  <div class="tiles" id="codec-tiles"></div>

  <h2>Mesh executor</h2>
  <div class="sub">persistent multi-chip datapath: long-lived SPMD
    programs fed depth-N in-flight batches &mdash; dispatch fill,
    coalescing across operations, spill absorption from the codec
    service</div>
  <div class="tiles" id="mesh-tiles"></div>

  <h2>Admission control</h2>
  <div class="sub">end-to-end overload protection: per-tenant token
    buckets, bounded request queues, SLO-driven shedding &mdash;
    per-hop, per-reason rejection counters (rejections climbing while
    goodput holds = healthy shed; everything falling together =
    collapse)</div>
  <div class="tiles" id="admission-tiles"></div>

  <h2>Small objects</h2>
  <div class="sub">tiny-object fast path: values inlined in OM
    metadata, needles packed into shared EC slabs, batched multi-key
    commits &mdash; slab census with dead-byte ratio (the compaction
    sweeper's backlog signal)</div>
  <div class="tiles" id="smallobj-tiles"></div>

  <h2>Shard map</h2>
  <div class="sub">sharded metadata plane: hash-partitioned OM rings
    behind an epoch-numbered root shard map &mdash; routing volume,
    moved-slot rejections, cross-shard 2PC outcomes, follower-read
    hit rate</div>
  <div class="tiles" id="shard-tiles"></div>
  <table id="shard-owners">
    <thead><tr><th>shard</th><th>slots owned</th><th>addresses</th>
      </tr></thead>
    <tbody></tbody>
  </table>

  <h2>Slow requests</h2>
  <div class="sub">flight recorder: traces retained past their per-op
    SLO &mdash; click a trace for its critical path (stage &rarr;
    &micro;s latency attribution)</div>
  <table id="slow-traces">
    <thead><tr><th>trace</th><th>op</th><th>duration</th>
      <th>SLO</th><th>spans</th></tr></thead>
    <tbody></tbody>
  </table>
  <div id="slow-detail"></div>

  <h2>Container &rarr; keys</h2>
  <div class="sub">which keys reference a container (the reference's
    ContainerKeyMapper view) &mdash; enter a container id</div>
  <div>
    <input id="ck-id" inputmode="numeric" placeholder="container id"
      style="padding:6px 8px;border:1px solid var(--border);
             border-radius:6px;background:var(--surface-2);
             color:var(--text-primary)">
    <button id="ck-go" style="padding:6px 12px;border:1px solid
      var(--border);border-radius:6px;background:var(--surface-2);
      color:var(--text-primary);cursor:pointer">look up</button>
  </div>
  <table id="ck">
    <thead><tr><th>container</th><th>keys</th></tr></thead>
    <tbody></tbody>
  </table>

  <h2>Unhealthy containers</h2>
  <table id="unhealthy">
    <thead><tr><th>container</th><th>states</th><th>replicas</th>
      <th>racks used/expected</th></tr></thead>
    <tbody></tbody>
  </table>
</div>
<script>
// state -> reserved status palette; always icon(dot)+label, never color alone
const STATE = {
  HEALTHY: ["var(--status-good)", "\\u2713"],
  STALE: ["var(--status-warning)", "\\u26a0"],
  DEAD: ["var(--status-critical)", "\\u2715"],
};
// every server-derived string goes through esc() before innerHTML —
// dn ids, racks, bucket labels etc. are external input to this page
function esc(s) {
  return String(s).replace(/[&<>"']/g, c => ({
    "&": "&amp;", "<": "&lt;", ">": "&gt;", '"': "&quot;", "'": "&#39;",
  }[c]));
}
function badge(state) {
  const [color, icon] = STATE[state] || ["var(--text-secondary)", "?"];
  return `<span class="badge"><span class="dot" style="background:${color}">` +
         `</span>${icon} ${esc(state)}</span>`;
}
function fmtBytes(n) {
  if (n == null) return "0 B";
  const units = ["B", "KiB", "MiB", "GiB", "TiB"];
  let i = 0;
  while (n >= 1024 && i < units.length - 1) { n /= 1024; i++; }
  return (i ? n.toFixed(1) : n) + " " + units[i];
}
function tile(k, v) {
  return `<div class="tile"><div class="v">${esc(v)}</div>` +
         `<div class="k">${esc(k)}</div></div>`;
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    document.getElementById("ts").textContent =
        "as of " + new Date(s.ts * 1000).toLocaleString();
    const ns = s.namespace || {};
    const tiles = [
      ["volumes", ns.volumes], ["buckets", ns.buckets],
      ["keys", ns.keys], ["bytes", fmtBytes(ns.bytes)],
      ["datanodes", (s.nodes || []).length],
    ];
    for (const [k, n] of Object.entries(s.containers || {}))
      tiles.push(["containers: " + k, n]);
    document.getElementById("tiles").innerHTML =
        tiles.map(([k, v]) => tile(k, v ?? 0)).join("");

    document.querySelector("#nodes tbody").innerHTML = (s.nodes || [])
      .map(n => `<tr><td>${esc(n.dn_id)}</td><td>${esc(n.rack ?? "")}</td>` +
                `<td>${badge(n.state)}</td><td>${esc(n.op_state ?? "")}</td>` +
                `<td>${fmtBytes(n.used_bytes)} / ` +
                `${fmtBytes(n.capacity_bytes)}</td></tr>`).join("");

    const pls = await (await fetch("/api/pipelines")).json();
    document.querySelector("#pipelines tbody").innerHTML = pls
      .map(p => `<tr><td>${esc(p.id)}</td><td>${esc(p.replication)}</td>` +
                `<td>${esc(p.state)}</td>` +
                `<td>${esc((p.nodes || []).join(", "))}</td></tr>`)
      .join("");

    document.querySelector("#health tbody").innerHTML =
        Object.entries(s.containers || {})
          .map(([k, v]) =>
            `<tr><td>${esc(k)}</td><td>${esc(v)}</td></tr>`).join("");

    const hm = await (await fetch("/api/heatmap")).json();
    const hcells = hm.cells || [];
    const hmax = Math.max(1, ...hcells.map(c => c.bytes));
    document.getElementById("heat").innerHTML =
      '<div class="heat-grid">' + hcells.map(c =>
        `<div class="heat-cell">` +
        `<div class="heat-fill" style="opacity:${
            (0.08 + 0.62 * c.bytes / hmax).toFixed(3)}"></div>` +
        `<div class="lbl">${esc(c.volume)}/${esc(c.bucket)}</div>` +
        `<div class="val">${fmtBytes(c.bytes)} &middot; ` +
        `${esc(c.keys)} keys</div></div>`).join("") + "</div>";

    const fs = await (await fetch("/api/filesizes")).json();
    const entries = Object.entries(fs);
    const max = Math.max(1, ...entries.map(([, v]) => v));
    document.getElementById("sizes").innerHTML = entries.map(([k, v]) =>
      `<div class="bar-row"><span class="bar-label">${esc(k)}</span>` +
      `<span class="bar" style="width:${(260 * v / max) | 0}px"></span>` +
      `<span class="bar-val">${esc(v)}</span></div>`).join("");
    document.querySelector("#sizes-table tbody").innerHTML = entries
      .map(([k, v]) =>
        `<tr><td>${esc(k)}</td><td>${esc(v)}</td></tr>`).join("");
    await refreshDu(duPath);
    const ti = await (await fetch("/api/insights/tables")).json();
    document.getElementById("insight-tiles").innerHTML =
      Object.entries(ti).filter(([, v]) => v > 0)
        .map(([k, v]) => tile(k, v)).join("") || tile("tables", "empty");
    const ok = await (await fetch("/api/insights/open_keys")).json();
    document.querySelector("#open-keys tbody").innerHTML = ok
      .map(r => `<tr><td>${esc(r.key)}</td><td>${esc(r.age_s)}</td>` +
                `<td>${r.hsync ? "yes" : ""}</td></tr>`).join("");
    // history needs the warehouse (a db_path'd Recon): skip the panel,
    // never abort the shared refresh, when it answers 404
    const hres = await fetch("/api/history/namespace");
    const hist = hres.ok ? await hres.json() : null;
    document.getElementById("trend").innerHTML = Array.isArray(hist)
      ? spark("keys", hist.map(h => h.keys ?? 0).reverse(), String) +
        spark("bytes", hist.map(h => h.bytes ?? 0).reverse(), fmtBytes)
      : '<span class="sub">no history warehouse</span>';
    const dk = await (await fetch("/api/insights/deleted_keys")).json();
    document.querySelector("#deleted-keys tbody").innerHTML = dk
      .map(r => `<tr><td>${esc(r.key)}</td><td>${fmtBytes(r.size)}</td>` +
                `<td>${esc(r.blocks)}</td><td>${esc(r.pending_s ?? "")}` +
                `</td></tr>`).join("") ||
      '<tr><td colspan="4">purge chain empty</td></tr>';
    const lc = await (await fetch("/api/lifecycle")).json();
    const lm = lc.metrics || {};
    document.getElementById("lifecycle-tiles").innerHTML = [
      tile("sweeper", lc.in_progress ? "sweeping"
                                     : (lc.term == null ? "idle (never "
                                        + "run)" : "idle")),
      tile("keys scanned", lm.keys_scanned ?? 0),
      tile("transitions", lm.transitions ?? 0),
      tile("bytes tiered", fmtBytes(lm.bytes_tiered ?? 0)),
      tile("expirations", lm.expirations ?? 0),
      tile("leader fences", lm.leader_fences ?? 0),
    ].join("");
    document.querySelector("#lifecycle-rules tbody").innerHTML =
      (lc.buckets || []).flatMap(b => (b.rules || []).map(r =>
        `<tr><td>${esc(b.bucket)}</td><td>${esc(r.id)}</td>` +
        `<td>${esc(r.prefix)}</td><td>${esc(r.age_days)}</td>` +
        `<td>${esc(r.action)}</td></tr>`)).join("") ||
      '<tr><td colspan="5">no lifecycle rules configured</td></tr>';
    const geo = await (await fetch("/api/replication")).json();
    const gm = geo.metrics || {};
    const glag = geo.lag || {};
    document.getElementById("geo-tiles").innerHTML = [
      tile("lag (entries)", glag.entries ?? 0),
      tile("lag (seconds)", glag.seconds ?? 0),
      tile("keys shipped", gm.keys_shipped ?? 0),
      tile("bytes shipped", fmtBytes(gm.bytes_shipped ?? 0)),
      tile("deletes shipped", gm.deletes_shipped ?? 0),
      tile("conflicts (LWW)", gm.conflicts ?? 0),
      tile("leader fences", gm.leader_fences ?? 0),
    ].join("");
    document.querySelector("#geo-rules tbody").innerHTML =
      (geo.buckets || []).flatMap(b => (b.rules || []).map(r =>
        `<tr><td>${esc(b.bucket)}</td><td>${esc(r.id)}</td>` +
        `<td>${esc(r.prefix)}</td><td>${esc(r.endpoint)}` +
        `${r.bucket ? "/" + esc(r.bucket) : ""}</td>` +
        `<td>${esc(r.scheme || "source")}</td></tr>`)).join("") ||
      '<tr><td colspan="5">no replication rules configured</td></tr>';
    const cx = await (await fetch("/api/codec")).json();
    document.getElementById("codec-tiles").innerHTML =
      cx.enabled === false
        ? tile("codec service", "disabled")
        : [
      tile("batch fill", `${Math.round((cx.fill_ratio ?? 0) * 100)}%`),
      tile("queue depth", cx.queue_depth ?? 0),
      tile("dispatches", cx.dispatches ?? 0),
      tile("ops/dispatch",
           (cx.ops_per_dispatch ?? 0).toFixed(2)),
      tile("multi-op dispatches", cx.multi_op_dispatches ?? 0),
      tile("linger flushes", cx.forced_flushes ?? 0),
      tile("deadline flushes", cx.deadline_flushes ?? 0),
      tile("tail flushes", cx.tail_flushes ?? 0),
      tile("starvation trips", cx.starvation_guard_trips ?? 0),
    ].join("");
    const mx = await (await fetch("/api/mesh")).json();
    document.getElementById("mesh-tiles").innerHTML =
      mx.enabled === false
        ? tile("mesh executor", "disabled")
        : mx.started === false
        ? [
      tile("mesh executor", "idle"),
      tile("spill", mx.spill_enabled ? "on" : "off"),
    ].join("")
        : [
      tile("devices", mx.devices ?? 0),
      tile("mode", (mx.programs_host_twin ?? 0) > 0
           && mx.programs_host_twin === mx.programs
           ? "host twin" : "device"),
      tile("batch fill", `${Math.round((mx.fill_ratio ?? 0) * 100)}%`),
      tile("queue depth", mx.queue_depth ?? 0),
      tile("dispatches", mx.dispatches ?? 0),
      tile("ops/dispatch", (mx.ops_per_dispatch ?? 0).toFixed(2)),
      tile("in-flight", `${mx.inflight ?? 0}/${mx.mesh_depth ?? 0}`),
      tile("max in-flight", mx.max_inflight ?? 0),
      tile("programs", mx.programs ?? 0),
      tile("spilled lanes", mx.spilled_lanes ?? 0),
      tile("spilled stripes", mx.spilled_stripes ?? 0),
      tile("spill", mx.spill_enabled ? "on" : "off"),
    ].join("");
    const ad = await (await fetch("/api/admission")).json();
    const ac = ad.counters || {};
    const hops = Object.values(ad.hops || {});
    document.getElementById("admission-tiles").innerHTML =
      hops.length === 0
        ? tile("admission", "no controllers installed")
        : [
      tile("enabled hops",
           hops.filter((h) => h.enabled).map((h) => h.hop)
               .join(" ") || "none"),
      tile("in-flight", hops.map(
           (h) => `${h.hop}:${h.inflight}/${h.queue_limit}`)
           .join(" ")),
      ...Object.entries(ac)
        .filter(([k]) => k.endsWith("_rejected_total")
                         || k.endsWith("_tenant_rejections"))
        .map(([k, v]) => tile(k.replace(/_/g, " "), v)),
      tile("tenants seen",
           hops.reduce((n, h) => n + (h.tenants?.length ?? 0), 0)),
    ].join("");
    const so = await (await fetch("/api/smallobj")).json();
    const soc = so.counters || {};
    const sos = so.slabs || {};
    document.getElementById("smallobj-tiles").innerHTML = [
      tile("inline puts", soc.inline_puts ?? 0),
      tile("inline gets", soc.inline_gets ?? 0),
      tile("needles packed", soc.needles_packed ?? 0),
      tile("needle gets", soc.needle_gets ?? 0),
      tile("slabs flushed", soc.slabs_flushed ?? 0),
      tile("commit batches", soc.commit_batches ?? 0),
      tile("live slabs", sos.count ?? 0),
      tile("dead bytes", sos.dead_bytes ?? 0),
      tile("worst dead ratio",
           `${Math.round((sos.worst_dead_ratio ?? 0) * 100)}%`),
      tile("compacted slabs", soc.compaction_slabs ?? 0),
      tile("compaction bytes", soc.compaction_bytes ?? 0),
      tile("inline max", so.knobs?.inline_max ?? 0),
      tile("needle max", so.knobs?.needle_max ?? 0),
    ].join("");
    const sh = await (await fetch("/api/shards")).json();
    const sc = sh.counters || {};
    const frTotal = (sc.follower_read_hits ?? 0) +
                    (sc.follower_read_misses ?? 0);
    document.getElementById("shard-tiles").innerHTML =
      sh.sharded === false
        ? tile("shard plane", "unsharded")
        : [
      tile("map epoch", sh.map?.epoch ?? sh.config?.epoch ?? 0),
      tile("slots", sh.map?.slot_count ?? sh.config?.slot_count ?? 0),
      tile("owned here", sh.config?.owned_slots ?? 0),
      tile("routes", sc.routes ?? 0),
      tile("moved rejections", sc.moved_rejections ?? 0),
      tile("2PC prepares", sc.cross_shard_prepares ?? 0),
      tile("2PC commits", sc.cross_shard_commits ?? 0),
      tile("2PC aborts", sc.cross_shard_aborts ?? 0),
      tile("follower-read hit", frTotal
           ? `${Math.round(100 * (sc.follower_read_hits ?? 0)
                           / frTotal)}%` : "n/a"),
      tile("lease renewals", sc.lease_renewals ?? 0),
    ].join("");
    document.querySelector("#shard-owners tbody").innerHTML =
      Object.entries(sh.map?.slots_per_shard || {})
        .map(([sid, n]) =>
          `<tr><td>${esc(sid)}</td><td>${esc(n)}</td>` +
          `<td>${esc((sh.map?.addresses || {})[sid] || "")}</td></tr>`)
        .join("") ||
      '<tr><td colspan="3">no root shard map on this OM</td></tr>';
    const sl = await (await fetch("/api/traces/slow")).json();
    document.querySelector("#slow-traces tbody").innerHTML =
      (sl.traces || []).map(t =>
        `<tr><td><a href="#" onclick="showTrace('${esc(t.traceId)}');` +
        `return false">${esc(t.traceId)}</a></td>` +
        `<td>${esc(t.root)}</td>` +
        `<td>${(t.durationMs ?? 0).toFixed(1)} ms</td>` +
        `<td>${(t.sloMs ?? 0).toFixed(0)} ms</td>` +
        `<td>${esc(t.spans)}</td></tr>`).join("") ||
      '<tr><td colspan="5">no traces over SLO retained</td></tr>';
    const uh = await (await fetch("/api/containers/unhealthy")).json();
    document.querySelector("#unhealthy tbody").innerHTML = uh
      .map(r => `<tr><td>${esc(r.container)}</td>` +
                `<td>${esc((r.states || []).join(", "))}</td>` +
                `<td>${esc(r.actual)}/${esc(r.expected)}</td>` +
                `<td>${esc(r.racks_used)}/${esc(r.racks_expected)}` +
                `</td></tr>`).join("") ||
      '<tr><td colspan="4">all containers healthy</td></tr>';
  } catch (e) {
    const ts = document.getElementById("ts");
    ts.innerHTML = '<span class="err"></span>';
    ts.firstChild.textContent = "failed to load: " + e;
  }
}
// one-hue inline-SVG sparkline with a direct label (no axes/legend:
// it shows shape; the label carries the current value)
function spark(label, vals, fmt) {
  if (!vals.length) vals = [0];
  const w = 220, h = 36, max = Math.max(1, ...vals);
  const step = vals.length > 1 ? w / (vals.length - 1) : 0;
  const pts = vals.map((v, i) =>
      `${(i * step).toFixed(1)},${(h - 2 - (h - 6) * v / max).toFixed(1)}`)
    .join(" ");
  return `<div class="bar-row"><span class="bar-label">${esc(label)}` +
    `</span><svg width="${w}" height="${h}" role="img" ` +
    `aria-label="${esc(label)} trend">` +
    `<polyline points="${pts}" fill="none" ` +
    `stroke="var(--series-1)" stroke-width="1.5"/></svg>` +
    `<span class="bar-val">${esc(fmt(vals[vals.length - 1]))}</span></div>`;
}
// container -> keys lookup (ContainerKeyMapper view)
async function lookupContainer() {
  const id = document.getElementById("ck-id").value.trim();
  if (!id) {  // the unfiltered map is every key of every container
    document.querySelector("#ck tbody").innerHTML =
      '<tr><td colspan="2">enter a container id first</td></tr>';
    return;
  }
  const res = await fetch("/api/containers/keys?id=" +
      encodeURIComponent(id));
  const m = res.ok ? await res.json() : {};
  document.querySelector("#ck tbody").innerHTML =
    Object.entries(m).map(([cid, keys]) =>
      `<tr><td>${esc(cid)}</td><td>${esc((keys || []).join(", "))}` +
      `</td></tr>`).join("") ||
    '<tr><td colspan="2">no keys reference it</td></tr>';
}
document.getElementById("ck-go").onclick = lookupContainer;
// slow-trace drill-down: the critical path is the answer to "where
// did this request spend its time" — render it as a stage table
async function showTrace(id) {
  const res = await fetch("/api/traces/slow?id=" +
      encodeURIComponent(id));
  const t = res.ok ? await res.json() : {};
  const cp = t.criticalPath || [];
  const total = cp.reduce((a, s) => a + s.micros, 0) || 1;
  document.getElementById("slow-detail").innerHTML =
    `<div class="sub">trace ${esc(id)} &mdash; ` +
    `${esc(t.root || "?")} ${(t.durationMs ?? 0).toFixed(1)} ms, ` +
    `${(t.spans || []).length} spans</div>` +
    '<table><thead><tr><th>stage</th><th>&micro;s</th><th>share</th>' +
    "</tr></thead><tbody>" +
    (cp.map(s =>
      `<tr><td>${esc(s.stage)}</td><td>${esc(s.micros)}</td>` +
      `<td>${(100 * s.micros / total).toFixed(1)}%</td></tr>`)
      .join("") ||
     '<tr><td colspan="3">trace no longer retained</td></tr>') +
    "</tbody></table>";
}
// du drill-down: click rows to descend, the header crumb to reset
let duPath = "/";
async function refreshDu(p) {
  const res = await fetch(
      "/api/nssummary?path=" + encodeURIComponent(p));
  if (p !== duPath) return;  // a newer navigation superseded this one
  if (!res.ok) {
    // the path vanished (bucket/dir deleted): reset to the root view
    // instead of rendering a dead path as an empty-but-healthy du
    if (p !== "/") { duPath = "/"; return refreshDu("/"); }
    document.getElementById("du-path").textContent =
        "du unavailable (" + res.status + ")";
    return;
  }
  const du = await res.json();
  if (p !== duPath) return;
  const crumb = document.getElementById("du-path");
  crumb.innerHTML = `<a href="#" id="du-root">/</a> ${esc(p)} &mdash; ` +
      `${esc(du.total_files ?? 0)} files, ` +
      `${fmtBytes(du.total_bytes ?? 0)}`;
  crumb.querySelector("#du-root").onclick =
      () => { duPath = "/"; refreshDu("/"); return false; };
  const rows = (du.children || []);
  document.querySelector("#du tbody").innerHTML = rows.map(c =>
    `<tr data-p="${esc(c.path)}" style="cursor:pointer">` +
    `<td>${esc(c.path)}</td><td>${esc(c.total_files)}</td>` +
    `<td>${fmtBytes(c.total_bytes)}</td></tr>`).join("") ||
    '<tr><td colspan="3">no children</td></tr>';
  for (const tr of document.querySelectorAll("#du tbody tr[data-p]"))
    tr.onclick = () => { duPath = tr.dataset.p; refreshDu(duPath); };
}
refresh();
setInterval(refresh, 10000);
</script>
</body>
</html>
"""
