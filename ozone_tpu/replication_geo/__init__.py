"""Geo-DR: cross-cluster asynchronous bucket replication.

Per-bucket replication rules (rules.py: destination cluster endpoint,
optional prefix filter, optional destination replication scheme)
persisted in replicated bucket metadata, enforced by a leader-singleton,
term-fenced ReplicationShipper (shipper.py) that tails the metadata
ring's WAL delta feed — the same stream Recon consumes — and replays
key commits/deletes to the remote cluster through the existing client
datapath.

Consistency shape (f4 OSDI '14 / Azure Storage ATC '12): strong inside
a cluster, asynchronous + ordered across clusters, last-writer-wins on
the rewrite fence so a destination-side overwrite beats a stale replay.
Apache Ozone 1.5 has no bucket-level cross-cluster replication; this is
a deliberate extension (docs/PARITY.md row 47).
"""

from ozone_tpu.replication_geo.rules import (  # noqa: F401
    GeoReplicationError,
    ReplicationRule,
    rules_from_s3_xml,
    rules_to_s3_xml,
    validate_rules,
)
from ozone_tpu.replication_geo.shipper import (  # noqa: F401
    ReplicationShipper,
    register_inprocess,
    unregister_inprocess,
)
