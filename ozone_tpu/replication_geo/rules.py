"""Geo replication rule model + S3 ?replication XML codec.

Per-bucket rules (prefix filter, destination cluster endpoint, optional
destination bucket/volume rename, optional destination replication
scheme) persisted in OM bucket metadata, so they replicate through the
metadata ring and survive failover exactly like lifecycle rules. The S3
gateway's Put/Get/DeleteBucketReplication verbs translate between the
AWS ReplicationConfiguration wire shape and this model; the shipper
(shipper.py) evaluates the same model — one definition, no drift.

Destination addressing rides the AWS shapes:

- ``<Bucket>arn:aws:s3:HOST:PORT::mirror</Bucket>`` — the ARN's region
  slot carries the destination cluster endpoint (AWS has global bucket
  names; a multi-cluster store needs the endpoint spelled out).
- ``<Destination><Endpoint>HOST:PORT</Endpoint><Bucket>mirror</Bucket>``
  — the explicit form for hand-rolled clients.

``<StorageClass>`` maps exactly like the lifecycle codec: a warm AWS
class becomes the cluster default EC scheme, a literal scheme string
("rs-6-3-1024k", "RATIS/THREE") passes through, absent means "keep the
source key's scheme". A scheme-converting rule (replicated source → EC
destination) re-encodes on device through the shared CodecService at
bulk QoS when the shipper replays it.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass

#: S3 StorageClass names accepted as "the destination's warm tier",
#: mapped to the default EC scheme at parse time (same set as
#: lifecycle/policy.py — one tiering vocabulary across both codecs)
_WARM_CLASSES = ("STANDARD_IA", "GLACIER", "GLACIER_IR", "DEEP_ARCHIVE",
                 "INTELLIGENT_TIERING", "ONEZONE_IA")

_NS = "http://s3.amazonaws.com/doc/2006-03-01/"
_ARN_PREFIX = "arn:aws:s3:"


class GeoReplicationError(ValueError):
    """Invalid rule / configuration (maps to S3 MalformedXML /
    InvalidArgument at the gateway)."""


@dataclass
class ReplicationRule:
    rule_id: str
    #: destination cluster endpoint — "host:port" (possibly a
    #: comma-separated OM-HA replica list) or an in-process test handle
    #: registered via shipper.register_inprocess
    endpoint: str = ""
    prefix: str = ""
    #: destination bucket name; "" = same name as the source bucket
    bucket: str = ""
    #: destination volume; "" = same volume name as the source
    volume: str = ""
    #: destination replication scheme; "" = keep the source key's scheme
    scheme: str = ""
    enabled: bool = True

    def validate(self) -> "ReplicationRule":
        if not self.rule_id:
            raise GeoReplicationError("rule needs a non-empty id")
        if not self.endpoint:
            raise GeoReplicationError(
                f"rule {self.rule_id!r} needs a destination cluster "
                "endpoint (host:port)")
        if self.scheme:
            from ozone_tpu.scm.pipeline import ReplicationConfig

            try:
                ReplicationConfig.parse(self.scheme)
            except ValueError as e:
                raise GeoReplicationError(
                    f"rule {self.rule_id!r}: bad destination scheme "
                    f"{self.scheme!r}: {e}")
        return self

    def matches(self, key: str) -> bool:
        return self.enabled and key.startswith(self.prefix)

    def to_json(self) -> dict:
        return {
            "id": self.rule_id,
            "endpoint": self.endpoint,
            "prefix": self.prefix,
            "bucket": self.bucket,
            "volume": self.volume,
            "scheme": self.scheme,
            "enabled": self.enabled,
        }

    @staticmethod
    def from_json(d: dict) -> "ReplicationRule":
        return ReplicationRule(
            rule_id=str(d.get("id", "")),
            endpoint=str(d.get("endpoint", "")),
            prefix=str(d.get("prefix", "")),
            bucket=str(d.get("bucket", "")),
            volume=str(d.get("volume", "")),
            scheme=str(d.get("scheme", "")),
            enabled=bool(d.get("enabled", True)),
        ).validate()


def validate_rules(rules: list[dict]) -> list[dict]:
    """Validate a rule list (wire dicts) and return the normalized
    dicts; raises GeoReplicationError on any bad rule or duplicate id."""
    out = []
    seen: set[str] = set()
    for d in rules:
        r = ReplicationRule.from_json(d)
        if r.rule_id in seen:
            raise GeoReplicationError(f"duplicate rule id {r.rule_id!r}")
        seen.add(r.rule_id)
        out.append(r.to_json())
    return out


def first_match(rules: list[ReplicationRule],
                key: str) -> ReplicationRule | None:
    """The first enabled rule whose prefix matches (rule order is the
    operator's priority order, like S3's)."""
    for r in rules:
        if r.matches(key):
            return r
    return None


# ------------------------------------------------------------- S3 XML
def _text(el: ET.Element, name: str) -> str:
    """Namespace-tolerant child text (AWS SDKs send the 2006-03-01
    namespace, hand-rolled clients usually don't)."""
    v = el.findtext(f"{{{_NS}}}{name}")
    if v is None:
        v = el.findtext(name)
    return (v or "").strip()


def _children(el: ET.Element, name: str) -> list[ET.Element]:
    return el.findall(f"{{{_NS}}}{name}") or el.findall(name)


def _parse_destination(rid: str, dest: ET.Element,
                       default_target: str
                       ) -> tuple[str, str, str, str]:
    """(endpoint, volume, bucket, scheme) from a <Destination>
    element. The ARN resource slot optionally carries a destination
    volume rename as `volume/bucket` — the GET codec renders rules
    that way, so a GET body re-PUTs without dropping the volume."""
    arn = _text(dest, "Bucket")
    endpoint = _text(dest, "Endpoint")
    bucket = ""
    if arn.startswith(_ARN_PREFIX):
        # arn:aws:s3:<endpoint>::<[volume/]bucket> — the endpoint
        # itself holds a colon (host:port), so split on the "::"
        # account separator
        rest = arn[len(_ARN_PREFIX):]
        ep, sep, bucket = rest.rpartition("::")
        if not sep:
            raise GeoReplicationError(
                f"rule {rid!r}: destination ARN {arn!r} carries no "
                "cluster endpoint (expected "
                "arn:aws:s3:HOST:PORT::bucket)")
        endpoint = endpoint or ep
    elif arn:
        bucket = arn  # bare name: endpoint must come from <Endpoint>
    if not endpoint:
        raise GeoReplicationError(
            f"rule {rid!r}: Destination needs a cluster endpoint "
            "(arn:aws:s3:HOST:PORT::bucket or an <Endpoint> element)")
    volume, sep, rest = bucket.partition("/")
    volume, bucket = (volume, rest) if sep else ("", bucket)
    sc = _text(dest, "StorageClass")
    scheme = "" if not sc else (default_target if sc in _WARM_CLASSES
                                else sc)
    return endpoint, volume, bucket, scheme


def rules_from_s3_xml(body: bytes,
                      default_target: str = "rs-6-3-1024k") -> list[dict]:
    """Parse a PutBucketReplication body into rule dicts. ``<Role>`` is
    accepted and ignored (no IAM here); ``<Priority>`` orders rules
    (lower first, AWS semantics); rules without one keep document
    order after all prioritized rules."""
    try:
        root = ET.fromstring(body)
    except ET.ParseError as e:
        raise GeoReplicationError(f"malformed XML: {e}")
    rule_els = _children(root, "Rule")
    if not rule_els:
        raise GeoReplicationError(
            "ReplicationConfiguration needs >= 1 Rule")
    parsed: list[tuple[float, int, dict]] = []
    for i, rel in enumerate(rule_els):
        rid = _text(rel, "ID") or f"rule-{i}"
        status = _text(rel, "Status") or "Enabled"
        prefix = _text(rel, "Prefix")
        for fel in _children(rel, "Filter"):
            prefix = _text(fel, "Prefix") or prefix
        dests = _children(rel, "Destination")
        if not dests:
            raise GeoReplicationError(
                f"rule {rid!r} has no Destination")
        endpoint, volume, bucket, scheme = _parse_destination(
            rid, dests[0], default_target)
        prio = _text(rel, "Priority")
        try:
            order = float(prio) if prio else float("inf")
        except ValueError:
            raise GeoReplicationError(
                f"rule {rid!r}: bad Priority {prio!r}")
        parsed.append((order, i, ReplicationRule(
            rule_id=rid, endpoint=endpoint, prefix=prefix,
            bucket=bucket, volume=volume, scheme=scheme,
            enabled=status.lower() == "enabled",
        ).validate().to_json()))
    parsed.sort(key=lambda t: (t[0], t[1]))
    return validate_rules([d for _, _, d in parsed])


def rules_to_s3_xml(rules: list[dict]) -> bytes:
    """Render stored rules as a GetBucketReplication body — rule order
    becomes explicit Priority so a GET body re-PUTs stably."""
    root = ET.Element("ReplicationConfiguration", xmlns=_NS)
    ET.SubElement(root, "Role").text = ""
    for n, d in enumerate(rules):
        r = ReplicationRule.from_json(d)
        rel = ET.SubElement(root, "Rule")
        ET.SubElement(rel, "ID").text = r.rule_id
        ET.SubElement(rel, "Priority").text = str(n + 1)
        ET.SubElement(rel, "Status").text = (
            "Enabled" if r.enabled else "Disabled")
        fel = ET.SubElement(rel, "Filter")
        ET.SubElement(fel, "Prefix").text = r.prefix
        dest = ET.SubElement(rel, "Destination")
        resource = (f"{r.volume}/{r.bucket}" if r.volume else r.bucket)
        ET.SubElement(dest, "Bucket").text = (
            f"{_ARN_PREFIX}{r.endpoint}::{resource}")
        if r.scheme:
            ET.SubElement(dest, "StorageClass").text = r.scheme
    return (b'<?xml version="1.0" encoding="UTF-8"?>'
            + ET.tostring(root))
