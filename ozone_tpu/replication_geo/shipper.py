"""ReplicationShipper: the term-fenced, WAL-tailing geo-DR replayer.

Leader-singleton control loop on the OM HA ring, modeled on
lifecycle/service.py. It tails the metadata store's WAL delta feed
(`store.get_updates_since`, the same stream Recon's indexes consume),
filters key commits/deletes of buckets that carry replication rules,
and replays each affected key's CURRENT source state to the remote
cluster through the existing client datapath.

Exactly-once-effective across a kill -9 of the shipper leader comes
from three properties (the lifecycle treatment applied to shipping):

1. **Term fencing**: every cursor checkpoint carries its fencing term
   and the deterministic apply (om/requests.GeoCheckpoint) rejects any
   checkpoint whose term is not the fenced one, so a deposed shipper's
   late checkpoints can never regress the cursor.
2. **Ship-then-checkpoint**: the WAL cursor is committed through the
   ring only after the page it covers replayed and acked at the
   destination. A crash between the two re-ships at most one page.
3. **Idempotent replay**: every shipped key carries the source row's
   object id in destination metadata (`geo-src-oid`); a re-applied
   page sees the marker and skips, so replays converge byte-exact with
   no duplicate writes and no resurrect-after-delete (deletes are
   fenced on the observed destination object id).

Conflict rule (Azure Storage ATC '12-style async geo-replication with
last-writer-wins): a destination-side overwrite beats a stale replay —
the replay commits under the rewrite fence (`expect_object_id` of the
destination version it supersedes) and loses deterministically with
KEY_MODIFIED when the destination moved, or is skipped outright when
the destination row is newer than the source commit. One bounded
caveat: when the destination row did NOT exist at replay lookup time,
the commit is necessarily unfenced (the fence can express "expect this
version" but not "expect absent"), so a destination-local CREATE
racing inside that lookup-to-commit window resolves to the replayed
version; the destination user's next overwrite wins as usual.

Scheme conversion (replicated source -> EC destination) rides the
destination client's normal EC write path, which submits stripes to
the shared CodecService at ``qos_class="bulk"`` — geo traffic can
never starve interactive reads.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from ozone_tpu.client import resilience
from ozone_tpu.om import requests as rq
from ozone_tpu.om.metadata import bucket_key
from ozone_tpu.replication_geo.rules import ReplicationRule, first_match
from ozone_tpu.storage.ids import StorageError
from ozone_tpu.utils.metrics import registry

log = logging.getLogger(__name__)

METRICS = registry("replication")

#: default per-ship-cycle wall-clock budget (seconds);
#: OZONE_TPU_GEO_DEADLINE_S overrides, 0 = unbounded
DEFAULT_SHIP_DEADLINE_S = 30.0

#: destination-key metadata carrying the replicated source version —
#: the idempotence/dedup marker and the bidirectional echo suppressor
GEO_META_OID = "geo-src-oid"
GEO_META_MTIME = "geo-src-mtime"
#: ...and the source bucket identity (/volume/bucket) that shipped it:
#: scopes tombstone replays and reconcile retirement so fan-in (many
#: source buckets sharing one destination bucket) never retires
#: replicas another source shipped. Distinct CLUSTERS fanning in from
#: identically-named source buckets still collide on this identity —
#: use distinct destination buckets/volume renames for that topology
#: (docs/OPERATIONS.md).
GEO_META_SRC = "geo-src"

_OM_ERRORS = (rq.OMError, StorageError)


class GeoFenced(Exception):
    """This shipper's term was fenced out by a newer leader."""


# ---------------------------------------------------- cluster resolution
_inproc: dict[str, Callable[[], object]] = {}
_inproc_lock = threading.Lock()


def register_inprocess(endpoint: str, client_fn: Callable[[], object]):
    """Register an in-process destination (tests / embedded clusters):
    `client_fn()` returns an OzoneClient for `endpoint`."""
    with _inproc_lock:
        _inproc[endpoint] = client_fn


def unregister_inprocess(endpoint: str) -> None:
    with _inproc_lock:
        _inproc.pop(endpoint, None)


class RemoteCluster:
    """Destination-cluster handle: an OzoneClient whose EC writes ride
    the shared CodecService at bulk QoS (geo traffic must never starve
    interactive work on the chip)."""

    def __init__(self, endpoint: str, oz, owned: bool = True):
        self.endpoint = endpoint
        self.oz = oz
        #: whether close() may tear down oz.om — False for in-process
        #: destinations, whose OzoneManager belongs to its own cluster
        self.owned = owned
        #: (volume, bucket) pairs already ensured to exist
        self._ensured: set[tuple[str, str]] = set()

    def ensure_bucket(self, volume: str, bucket: str,
                      replication: str) -> None:
        if (volume, bucket) in self._ensured:
            return
        try:
            self.oz.om.create_volume(volume)
        except _OM_ERRORS as e:
            if getattr(e, "code", "") != rq.VOLUME_ALREADY_EXISTS:
                raise
        try:
            self.oz.om.create_bucket(volume, bucket, replication)
        except _OM_ERRORS as e:
            if getattr(e, "code", "") != rq.BUCKET_ALREADY_EXISTS:
                raise
        # a pre-existing FSO destination cannot serve the replay path
        # (tombstones need the fenced flat-key DeleteKey): refuse LOUDLY
        # at first contact instead of stalling on the first tombstone
        info = self.oz.om.bucket_info(volume, bucket)
        if info.get("layout") == "FILE_SYSTEM_OPTIMIZED":
            raise StorageError(
                rq.INVALID_REQUEST,
                f"geo destination /{volume}/{bucket} at {self.endpoint} "
                "is FILE_SYSTEM_OPTIMIZED; replication needs an "
                "OBS/LEGACY destination bucket (docs/OPERATIONS.md)")
        self._ensured.add((volume, bucket))

    def close(self) -> None:
        if not self.owned:
            return
        close = getattr(self.oz.om, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                log.debug("geo: closing remote %s failed", self.endpoint,
                          exc_info=True)


def resolve_cluster(endpoint: str, tls=None) -> RemoteCluster:
    """Destination handle for a rule endpoint: an in-process registrant
    when one exists (tests, embedded pairs), else a real gRPC dial of
    the remote OM(-HA list) + SCM for datanode address learning — the
    same bring-up as tools/cli._client."""
    from ozone_tpu.client.ozone_client import OzoneClient

    with _inproc_lock:
        fn = _inproc.get(endpoint)
    if fn is not None:
        base = fn()
        return RemoteCluster(endpoint, OzoneClient(
            base.om, base.clients,
            ratis_clients=base.ratis_clients, qos_class="bulk"),
            owned=False)
    from ozone_tpu.client.dn_client import DatanodeClientFactory
    from ozone_tpu.net.om_service import GrpcOmClient
    from ozone_tpu.net.scm_service import GrpcScmClient

    clients = DatanodeClientFactory()
    clients.tls = tls
    om = GrpcOmClient(endpoint, clients=clients, tls=tls)
    try:
        scm = GrpcScmClient(endpoint, tls=tls)
        for dn_id, addr in scm.node_addresses().items():
            clients.register_remote(dn_id, addr)
        scm.close()
    except StorageError:
        log.warning("geo: SCM at %s unreachable; datanode addresses "
                    "will be learned from allocations", endpoint)
    return RemoteCluster(endpoint, OzoneClient(om, clients,
                                               qos_class="bulk"))


# ------------------------------------------------------------- shipper
class ReplicationShipper:
    """Per-bucket async cross-cluster replication (geo-DR).

    ``term_fn`` returns the fencing term (the metadata ring's raft term
    under HA; 0 standalone). ``leader_fn`` gates each cycle — only the
    ring leader ships. ``clients_fn`` resolves the source datanode
    client factory lazily (daemons learn addresses from heartbeats).
    ``resolver`` maps a rule endpoint to a RemoteCluster (defaults to
    resolve_cluster; tests inject in-process destinations)."""

    STATE_KEY = "geo_state"

    def __init__(self, om, clients=None, clients_fn=None,
                 term_fn: Optional[Callable[[], int]] = None,
                 leader_fn: Optional[Callable[[], bool]] = None,
                 resolver: Optional[Callable[[str], RemoteCluster]] = None,
                 throttle=None, page: int = 64,
                 ship_deadline_s: Optional[float] = None, tls=None):
        self.om = om
        self._clients = clients
        self._clients_fn = clients_fn
        self.term_fn = term_fn or (lambda: 0)
        self.leader_fn = leader_fn or (lambda: True)
        self.throttle = throttle
        self.page = page
        self.tls = tls
        self.resolver = resolver or (
            lambda ep: resolve_cluster(ep, tls=self.tls))
        if ship_deadline_s is None:
            from ozone_tpu.utils.config import env_float

            ship_deadline_s = env_float("OZONE_TPU_GEO_DEADLINE_S",
                                        DEFAULT_SHIP_DEADLINE_S)
        self.ship_deadline_s = ship_deadline_s
        self._fenced_term: Optional[int] = None
        self._remotes: dict[str, RemoteCluster] = {}
        # one cycle at a time per service (run-now racing the daemon
        # cadence would interleave same-term cursor checkpoints)
        self._ship_lock = threading.Lock()
        #: wall time the current non-zero lag was first observed (the
        #: seconds-behind fallback when pending deletes carry no mtime)
        self._lag_since: Optional[float] = None

    # ------------------------------------------------------------ plumbing
    def clients(self):
        if self._clients_fn is not None:
            return self._clients_fn()
        return self._clients

    def source_client(self):
        from ozone_tpu.client.ozone_client import OzoneClient

        # bulk QoS on the SOURCE side too: shipping a large EC bucket
        # must not flood the shared codec service's decode queue at
        # interactive priority
        return OzoneClient(self.om, self.clients(), qos_class="bulk")

    def state(self) -> dict:
        return self.om.store.get("system", self.STATE_KEY) or {}

    def remote(self, endpoint: str) -> RemoteCluster:
        r = self._remotes.get(endpoint)
        if r is None:
            r = self._remotes[endpoint] = self.resolver(endpoint)
        return r

    def close(self) -> None:
        for r in self._remotes.values():
            r.close()
        self._remotes.clear()

    def _checkpoint(self, term: int, cursor: dict,
                    stats: Optional[dict] = None,
                    bootstrapped: Optional[list] = None,
                    fence: bool = False) -> None:
        try:
            self.om.submit(rq.GeoCheckpoint(
                term=term, cursor=cursor, stats=stats or {},
                bootstrapped=bootstrapped, fence=fence))
        except rq.OMError as e:
            if e.code == rq.GEO_FENCED:
                METRICS.counter("leader_fences").inc()
                raise GeoFenced(str(e))
            raise

    def _fence(self, term: int) -> None:
        """Claim the shipper role for this term (idempotent per term):
        after this commits, checkpoints from any OLDER term are
        deterministically rejected on every replica."""
        if self._fenced_term == term:
            return
        self._checkpoint(term, cursor=self.state().get("cursor", {}),
                         fence=True)
        self._fenced_term = term

    def _bucket_rules(self) -> dict[str, tuple[dict, list[ReplicationRule]]]:
        out: dict[str, tuple[dict, list[ReplicationRule]]] = {}
        for bk, brow in self.om.store.iterate("buckets"):
            raw = brow.get("geo_replication") or []
            if not raw:
                continue
            try:
                rules = [ReplicationRule.from_json(d) for d in raw]
            except ValueError as e:
                log.warning("geo: bucket %s has invalid replication "
                            "rules (%s); skipping", bk, e)
                continue
            out[bk] = (brow, rules)
        return out

    # ---------------------------------------------------------------- lag
    def lag(self, buckets: Optional[dict] = None) -> dict:
        """WAL-head lag: journal entries between the shipped cursor and
        the head, plus a seconds-behind estimate (oldest pending
        matching commit's mtime; wall-clock since lag appeared when only
        tombstones are pending). Updates the replication.* gauges.
        `buckets` lets the ship cycle reuse its own rule scan."""
        state = self.state()
        txid = int((state.get("cursor") or {}).get("txid", 0))
        updates, head, _complete = self.om.store.get_updates_since(txid)
        if buckets is None:
            buckets = self._bucket_rules()
        entries = 0
        oldest: Optional[float] = None
        for _utx, table, key, value in updates:
            if table != "keys":
                continue
            bk = self._bucket_of(key)
            if bk not in buckets:
                continue
            entries += 1
            if value is not None:
                ts = float(value.get("modified")
                           or value.get("created") or 0.0)
                if ts and (oldest is None or ts < oldest):
                    oldest = ts
        now = time.time()
        if entries:
            if self._lag_since is None:
                self._lag_since = now
            seconds = (now - oldest if oldest is not None
                       else now - self._lag_since)
        else:
            self._lag_since = None
            seconds = 0.0
        seconds = max(0.0, seconds)
        METRICS.gauge("lag_entries").set(entries)
        METRICS.gauge("lag_seconds").set(round(seconds, 3))
        return {"entries": entries, "seconds": round(seconds, 3),
                "head_txid": head, "cursor_txid": txid}

    @staticmethod
    def _bucket_of(store_key: str) -> str:
        """/vol/bucket/key... -> /vol/bucket (snapshot rows excluded)."""
        if store_key.startswith("/.snap"):
            return ""
        parts = store_key.split("/", 3)
        return f"/{parts[1]}/{parts[2]}" if len(parts) >= 4 else ""

    # --------------------------------------------------------------- cycle
    def run_once(self, max_entries: Optional[int] = None) -> dict:
        """One ship cycle: bootstrap newly-ruled buckets, then tail the
        WAL delta from the replicated cursor and replay affected keys.
        Safe to call on any node — followers return
        {"skipped": "not_leader"}. `max_entries` bounds the WAL scan
        (tests / incremental ticks)."""
        if not self.leader_fn():
            return {"skipped": "not_leader"}
        if not self._ship_lock.acquire(blocking=False):
            return {"skipped": "ship_in_progress"}
        try:
            return self._run_once_locked(max_entries)
        finally:
            self._ship_lock.release()

    def _run_once_locked(self, max_entries: Optional[int]) -> dict:
        term = int(self.term_fn())
        stats = {"entries_scanned": 0, "keys_shipped": 0,
                 "deletes_shipped": 0, "conflicts": 0, "in_sync": 0,
                 "skipped": 0, "failed": 0, "bytes": 0, "pages": 0,
                 "bootstrapped": 0, "complete": False}
        t0 = time.monotonic()
        buckets = self._bucket_rules()
        if not buckets:
            # no bucket carries rules: nothing to fence, tail or
            # checkpoint — a rule-less cluster must see ZERO geo ring
            # traffic (no WAL self-churn, no background ring commits)
            stats["complete"] = True
            METRICS.gauge("lag_entries").set(0)
            METRICS.gauge("lag_seconds").set(0.0)
            return stats
        try:
            with resilience.start("geo_ship",
                                  seconds=self.ship_deadline_s):
                self._fence(term)
                self._ship(term, buckets, stats, max_entries)
        except GeoFenced:
            stats["fenced"] = True
            log.info("geo: shipper fenced out (term %d)", term)
        except StorageError as e:
            if e.code != resilience.DEADLINE_EXCEEDED:
                raise
            # budget spent mid-cycle: everything checkpointed so far is
            # durable; the un-checkpointed tail re-ships next cycle
            stats["deadline_exceeded"] = True
        METRICS.timer("ship_seconds").update(time.monotonic() - t0)
        METRICS.counter("cycles").inc()
        self.lag(buckets=buckets)
        return stats

    def _ship(self, term: int, buckets: dict, stats: dict,
              max_entries: Optional[int]) -> None:
        state = self.state()
        cursor = dict(state.get("cursor") or {})
        txid = int(cursor.get("txid", 0))
        # bootstrap: full reconcile of buckets whose rules predate their
        # WAL coverage (rule installed after the journal rolled, or a
        # brand-new rule over an existing namespace). Entries journaled
        # DURING the reconcile re-ship via the delta path — harmless,
        # the geo-src-oid marker makes the second pass a no-op.
        boot = set(state.get("bootstrapped") or []) & set(buckets)
        for bk in sorted(set(buckets) - boot):
            brow, rules = buckets[bk]
            self._reconcile_bucket(bk, brow, rules, stats)
            boot.add(bk)
            stats["bootstrapped"] += 1
            METRICS.counter("bootstraps").inc()
            self._checkpoint(term, cursor={"txid": txid},
                             bootstrapped=sorted(boot),
                             stats=self._stats_row(stats))
        updates, head, complete = self.om.store.get_updates_since(txid)
        if not complete:
            # journal rolled past our cursor (leader was down too long):
            # the delta is gone — reconcile every ruled bucket, then
            # resume tailing from the current head
            METRICS.counter("journal_gaps").inc()
            stats["journal_gap"] = True
            for bk in sorted(buckets):
                brow, rules = buckets[bk]
                self._reconcile_bucket(bk, brow, rules, stats)
            self._checkpoint(term, cursor={"txid": head},
                             bootstrapped=sorted(boot),
                             stats=self._stats_row(stats))
            stats["complete"] = True
            return
        truncated = False
        if max_entries is not None and len(updates) > max_entries:
            truncated = True  # a bounded tick: report complete=False
            updates = updates[:max_entries]
        # page the tail: per page, coalesce entries by key (the replay
        # ships the CURRENT source state, so N entries of one key cost
        # one replay) — ship, then checkpoint the covered txid
        i = 0
        while i < len(updates):
            resilience.check_deadline("geo_page")
            page_keys: dict[tuple[str, str], None] = {}
            last_txid = txid
            while i < len(updates) and len(page_keys) < self.page:
                utx, table, key, _value = updates[i]
                i += 1
                last_txid = utx
                stats["entries_scanned"] += 1
                if table != "keys":
                    continue
                bk = self._bucket_of(key)
                if bk not in buckets:
                    continue
                page_keys.setdefault((bk, key.split("/", 3)[3]), None)
            for bk, name in page_keys:
                brow, rules = buckets[bk]
                self._replay_key(brow, rules, name, stats)
            self._checkpoint(term, cursor={"txid": last_txid},
                             bootstrapped=sorted(boot),
                             stats=self._stats_row(stats))
            stats["pages"] += 1
            METRICS.counter("pages_shipped").inc()
            txid = last_txid
        stats["complete"] = not truncated

    @staticmethod
    def _stats_row(stats: dict) -> dict:
        """The durable per-cycle summary riding each checkpoint (the
        `replication status` / Recon "last cycle" view)."""
        return {
            "entries_scanned": stats["entries_scanned"],
            "keys_shipped": stats["keys_shipped"],
            "deletes_shipped": stats["deletes_shipped"],
            "conflicts": stats["conflicts"],
            "failed": stats["failed"],
            "bytes": stats["bytes"],
            "updated": round(time.time(), 3),
        }

    # ----------------------------------------------------------- reconcile
    def _reconcile_bucket(self, bk: str, brow: dict,
                          rules: list[ReplicationRule],
                          stats: dict) -> None:
        """Anti-entropy pass over one bucket: ship every matching source
        key, then delete destination replicas (ours, by marker) whose
        source key is gone. Idempotent — safe to re-run after a crash
        mid-pass."""
        volume, bucket = brow["volume"], brow["name"]
        live: set[tuple[str, str, str, str]] = set()
        for info in self.om.list_keys(volume, bucket):
            resilience.check_deadline("geo_reconcile")
            name = info["name"]
            rule = first_match(rules, name)
            if rule is None:
                continue
            self._replay_key(brow, rules, name, stats)
            live.add((rule.endpoint, rule.volume or volume,
                      rule.bucket or bucket, name))
        # retire OUR stale replicas at each destination (a source key
        # deleted while the journal was gone leaves no tombstone to
        # replay; the marker scopes the sweep to keys we shipped)
        for rule in rules:
            if not rule.enabled:
                continue
            dvol = rule.volume or volume
            dbkt = rule.bucket or bucket
            remote = self.remote(rule.endpoint)
            try:
                dkeys = remote.oz.om.list_keys(dvol, dbkt, rule.prefix)
            except _OM_ERRORS as e:
                code = getattr(e, "code", "")
                if code not in (rq.BUCKET_NOT_FOUND,
                                rq.VOLUME_NOT_FOUND):
                    raise
                continue  # destination bucket not created yet
            for dinfo in dkeys:
                meta = dinfo.get("metadata") or {}
                if meta.get(GEO_META_SRC) != bk:
                    # locally-written destination key, or a replica
                    # ANOTHER source bucket/cluster shipped into this
                    # shared destination — never ours to retire
                    continue
                if (rule.endpoint, dvol, dbkt, dinfo["name"]) in live:
                    continue
                self._delete_at(remote, dvol, dbkt, dinfo["name"],
                                dinfo, stats)

    # -------------------------------------------------------------- replay
    def _replay_key(self, brow: dict, rules: list[ReplicationRule],
                    name: str, stats: dict) -> None:
        """Replay one source key's current state to its rule's
        destination, retrying transient failures under the ambient
        deadline. A key that still fails after the retries aborts the
        cycle WITHOUT checkpointing its page (at-least-once: the page
        re-ships next cycle instead of silently skipping the key)."""
        rule = first_match(rules, name)
        if rule is None:
            stats["skipped"] += 1
            return
        policy = resilience.RetryPolicy(max_attempts=3)
        attempt = 0
        while True:
            try:
                self._replay_once(brow, rule, name, stats)
                return
            except _OM_ERRORS as e:
                if getattr(e, "code", "") == resilience.DEADLINE_EXCEEDED:
                    raise
                log.warning("geo: replay of %s/%s/%s -> %s failed "
                            "(attempt %d): %s", brow["volume"],
                            brow["name"], name, rule.endpoint,
                            attempt + 1, e)
                if not policy.sleep(attempt):
                    stats["failed"] += 1
                    METRICS.counter("ship_failures").inc()
                    raise
                attempt += 1

    def _replay_once(self, brow: dict, rule: ReplicationRule,
                     name: str, stats: dict) -> None:
        volume, bucket = brow["volume"], brow["name"]
        dvol = rule.volume or volume
        dbkt = rule.bucket or bucket
        remote = self.remote(rule.endpoint)
        try:
            info = self.om.lookup_key(volume, bucket, name)
        except rq.OMError as e:
            if e.code != rq.KEY_NOT_FOUND:
                raise
            self._replay_delete(remote, dvol, dbkt, name,
                                bucket_key(volume, bucket), stats)
            return
        remote.ensure_bucket(dvol, dbkt,
                             rule.scheme or brow.get("replication")
                             or str(info.get("replication", "")))
        src_oid = str(info.get("object_id", ""))
        src_ts = float(info.get("modified") or info.get("created") or 0.0)
        dinfo = self._dest_lookup(remote, dvol, dbkt, name)
        fence_oid = ""
        if dinfo is not None:
            dmeta = dinfo.get("metadata") or {}
            if dmeta.get(GEO_META_OID) == src_oid:
                stats["in_sync"] += 1  # this exact version already landed
                return
            src_meta = info.get("metadata") or {}
            if src_meta.get(GEO_META_OID) == str(dinfo.get("object_id")):
                stats["in_sync"] += 1  # bidirectional echo: source row IS
                return                 # a replica of the destination row
            dest_ts = float(dinfo.get("modified")
                            or dinfo.get("created") or 0.0)
            if GEO_META_OID not in dmeta and dest_ts > src_ts:
                # last-writer-wins: a destination-side overwrite newer
                # than this source commit is authoritative
                stats["conflicts"] += 1
                METRICS.counter("conflicts").inc()
                return
            fence_oid = str(dinfo.get("object_id", ""))
        src = self.source_client()
        from ozone_tpu.client.ozone_client import OzoneBucket

        data = OzoneBucket(src, volume, bucket).read_key_info(info)
        if self.throttle is not None and data.size:
            self.throttle.take(int(data.size))
        meta = dict(info.get("metadata") or {})
        meta[GEO_META_OID] = src_oid
        meta[GEO_META_MTIME] = repr(src_ts)
        meta[GEO_META_SRC] = bucket_key(volume, bucket)
        scheme = rule.scheme or str(info.get("replication", "")) or None
        dbucket = OzoneBucket(remote.oz, dvol, dbkt)
        h = dbucket.open_key(name, scheme, metadata=meta)
        # rewrite fence: commit only if the destination row is still the
        # version this replay observed — a concurrent destination-side
        # overwrite wins with KEY_MODIFIED (last-writer-wins)
        h._session.expect_object_id = fence_oid
        try:
            h.write(data)
            h.close()
        except _OM_ERRORS as e:
            if getattr(e, "code", "") == rq.KEY_MODIFIED:
                stats["conflicts"] += 1
                METRICS.counter("conflicts").inc()
                return
            raise
        stats["keys_shipped"] += 1
        stats["bytes"] += int(data.size)
        METRICS.counter("keys_shipped").inc()
        METRICS.counter("bytes_shipped").inc(int(data.size))

    def _dest_lookup(self, remote: RemoteCluster, dvol: str, dbkt: str,
                     name: str) -> Optional[dict]:
        try:
            return remote.oz.om.lookup_key(dvol, dbkt, name)
        except _OM_ERRORS as e:
            code = getattr(e, "code", "")
            if code in (rq.KEY_NOT_FOUND, rq.BUCKET_NOT_FOUND,
                        rq.VOLUME_NOT_FOUND):
                return None
            raise

    def _replay_delete(self, remote: RemoteCluster, dvol: str, dbkt: str,
                       name: str, src: str, stats: dict) -> None:
        dinfo = self._dest_lookup(remote, dvol, dbkt, name)
        if dinfo is None:
            stats["in_sync"] += 1  # already gone (or never shipped)
            return
        meta = dinfo.get("metadata") or {}
        if meta.get(GEO_META_SRC) != src:
            # the destination row was written locally at the
            # destination — or shipped there by a DIFFERENT source
            # fanning into the same bucket — never by us: it wins
            # (deleting it would destroy data we do not own)
            stats["conflicts"] += 1
            METRICS.counter("conflicts").inc()
            return
        self._delete_at(remote, dvol, dbkt, name, dinfo, stats)

    def _delete_at(self, remote: RemoteCluster, dvol: str, dbkt: str,
                   name: str, dinfo: dict, stats: dict) -> None:
        try:
            remote.oz.om.delete_key(
                dvol, dbkt, name,
                expect_object_id=str(dinfo.get("object_id", "")))
        except _OM_ERRORS as e:
            code = getattr(e, "code", "")
            if code == rq.KEY_MODIFIED:
                # overwritten at the destination between our lookup and
                # the fenced delete: the overwrite wins
                stats["conflicts"] += 1
                METRICS.counter("conflicts").inc()
                return
            if code == rq.KEY_NOT_FOUND:
                return  # a concurrent replay already retired it
            raise
        stats["deletes_shipped"] += 1
        METRICS.counter("deletes_shipped").inc()
