"""Container balancer: move replicas from over- to under-utilized nodes.

Mirror of the reference's ContainerBalancer (server-scm container/balancer/
ContainerBalancer.java:42 + ContainerBalancerTask with FindSourceStrategy/
FindTargetStrategy): nodes outside a utilization band around the cluster
average become sources/targets; each iteration moves up to a configured
amount of data by scheduling replicate+delete command pairs through the
node command queues. Iteration state is queryable (StatefulService analog).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ozone_tpu.scm.container_manager import ContainerManager
from ozone_tpu.scm.node_manager import NodeManager
from ozone_tpu.scm.replication_manager import (
    DeleteReplicaCommand,
    ReplicateCommand,
)
from ozone_tpu.storage.ids import ContainerState

log = logging.getLogger(__name__)


@dataclass
class BalancerConfig:
    threshold: float = 0.10  # +-10% band around average utilization
    max_moves_per_iteration: int = 5
    max_size_per_iteration: int = 10 * 1024**3


@dataclass
class Move:
    container_id: int
    replica_index: int
    source: str
    target: str
    size: int


@dataclass
class BalancerStatus:
    running: bool = False
    iterations: int = 0
    moves_scheduled: int = 0
    bytes_scheduled: int = 0
    last_iteration_moves: list[Move] = field(default_factory=list)


class ContainerBalancer:
    def __init__(
        self,
        containers: ContainerManager,
        nodes: NodeManager,
        config: BalancerConfig = None,
    ):
        self.containers = containers
        self.nodes = nodes
        # fresh default per balancer: the config is mutated by restores
        # and operator overrides, so sharing one instance would leak
        # settings across SCMs in the same process
        self.config = config if config is not None else BalancerConfig()
        self.status = BalancerStatus()

    def _utilization(self) -> dict[str, float]:
        out = {}
        for n in self.nodes.healthy_in_service():
            out[n.dn_id] = (
                n.used_bytes / n.capacity_bytes if n.capacity_bytes else 0.0
            )
        return out

    def run_iteration(self) -> list[Move]:
        """One balancing iteration: schedule up to max_moves moves."""
        util = self._utilization()
        if not util:
            return []
        avg = sum(util.values()) / len(util)
        over = sorted(
            (d for d, u in util.items() if u > avg + self.config.threshold),
            key=lambda d: -util[d],
        )
        under = sorted(
            (d for d, u in util.items() if u < avg - self.config.threshold),
            key=lambda d: util[d],
        )
        moves: list[Move] = []
        budget = self.config.max_size_per_iteration
        for src in over:
            if len(moves) >= self.config.max_moves_per_iteration or not under:
                break
            # candidate replicas on the source, largest containers first
            cands = [
                (c, c.replicas[src])
                for c in self.containers.containers()
                if src in c.replicas
                and c.state in (ContainerState.CLOSED,
                                ContainerState.QUASI_CLOSED)
            ]
            cands.sort(key=lambda t: -t[0].used_bytes)
            for c, replica in cands:
                if len(moves) >= self.config.max_moves_per_iteration:
                    break
                if c.used_bytes > budget:
                    continue
                target = next(
                    (t for t in under if t not in c.replicas), None
                )
                if target is None:
                    continue
                moves.append(
                    Move(c.id, replica.replica_index, src, target,
                         c.used_bytes)
                )
                budget -= c.used_bytes
                break  # one move per source per iteration, like the ref

        for m in moves:
            # move = copy to target, then delete from source once copied;
            # delete is queued on the source after the target reports the
            # replica (simplified: queue both, target executes copy first
            # because commands deliver in heartbeat order)
            self.nodes.queue_command(
                m.target,
                ReplicateCommand(m.container_id, source=m.source,
                                 target=m.target,
                                 replica_index=m.replica_index),
            )
            self.nodes.queue_command(
                m.source, DeleteReplicaCommand(m.container_id,
                                               m.replica_index)
            )
        self.status.iterations += 1
        self.status.moves_scheduled += len(moves)
        self.status.bytes_scheduled += sum(m.size for m in moves)
        self.status.last_iteration_moves = moves
        return moves
