"""SCM block-deletion transaction log + deleting service.

Mirror of the reference's deletion chain (server-scm block/
DeletedBlockLogImpl + SCMBlockDeletingService: the OM hands deleted keys'
blocks to SCM as transactions; the service batches per-datanode
DeleteBlocksCommands onto heartbeats; datanodes delete chunks and ack by
transaction id; acked transactions retire, unacked ones retry up to a
cap). This closes the delete path the reference routes through SCM rather
than the OM talking to datanodes directly.
"""

from __future__ import annotations

import itertools
import logging
import threading
from dataclasses import dataclass, field

from ozone_tpu.scm.node_manager import NodeManager
from ozone_tpu.storage.ids import BlockID

log = logging.getLogger(__name__)


@dataclass
class DeleteBlocksCommand:
    """Per-datanode deletion batch riding a heartbeat."""

    tx_ids: list[int]
    blocks: list[BlockID]


@dataclass
class _DeleteTx:
    tx_id: int
    block: BlockID
    datanodes: list[str]
    acked: set[str] = field(default_factory=set)
    attempts: int = 0


class DeletedBlockLog:
    """Pending deletion transactions (DeletedBlockLogImpl analog)."""

    MAX_ATTEMPTS = 5

    def __init__(self):
        self._txs: dict[int, _DeleteTx] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    def add(self, block: BlockID, datanodes: list[str]) -> int:
        with self._lock:
            tx = _DeleteTx(next(self._ids), block, list(datanodes))
            self._txs[tx.tx_id] = tx
            return tx.tx_id

    def pending_for(self, dn_id: str, limit: int = 100) -> list[_DeleteTx]:
        with self._lock:
            out = []
            for tx in self._txs.values():
                if dn_id in tx.datanodes and dn_id not in tx.acked:
                    out.append(tx)
                    if len(out) >= limit:
                        break
            return out

    def ack(self, dn_id: str, tx_ids: list[int]) -> None:
        with self._lock:
            for t in tx_ids:
                tx = self._txs.get(t)
                if tx is None:
                    continue
                tx.acked.add(dn_id)
                if tx.acked >= set(tx.datanodes):
                    del self._txs[tx.tx_id]

    def retire_failed(self) -> list[_DeleteTx]:
        """Drop transactions that exceeded the retry cap."""
        with self._lock:
            dead = [
                t for t in self._txs.values()
                if t.attempts > self.MAX_ATTEMPTS
            ]
            for t in dead:
                del self._txs[t.tx_id]
            return dead

    def pending_count(self) -> int:
        return len(self._txs)


class BlockDeletingService:
    """Queues per-DN DeleteBlocksCommands (SCMBlockDeletingService)."""

    def __init__(self, deleted_log: DeletedBlockLog, nodes: NodeManager,
                 batch: int = 100):
        self.log = deleted_log
        self.nodes = nodes
        self.batch = batch

    def run_once(self) -> int:
        queued = 0
        for n in self.nodes.healthy_in_service():
            txs = self.log.pending_for(n.dn_id, self.batch)
            if not txs:
                continue
            for t in txs:
                t.attempts += 1
            self.nodes.queue_command(
                n.dn_id,
                DeleteBlocksCommand(
                    [t.tx_id for t in txs], [t.block for t in txs]
                ),
            )
            queued += len(txs)
        self.log.retire_failed()
        return queued
