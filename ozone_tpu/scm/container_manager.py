"""SCM container manager: lifecycle + replica tracking + EC/Ratis writable
container pools + block allocation.

Mirrors server-scm's ContainerManagerImpl/ContainerStateManagerImpl
(lifecycle OPEN->CLOSING->CLOSED->DELETED), replica maps fed by container
reports, BlockManagerImpl.allocateBlock:146 and the writable-container
providers (WritableECContainerProvider.java:53,95-174 — a pool of open EC
containers, one per placement set, new container when none fits;
WritableRatisContainerProvider for replicated pipelines).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Optional

from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.scm.node_manager import NodeManager
from ozone_tpu.scm.placement import PlacementPolicy
from ozone_tpu.scm.pipeline import (
    Pipeline,
    PipelineState,
    ReplicationConfig,
    ReplicationType,
)
from ozone_tpu.storage.ids import ContainerState

log = logging.getLogger(__name__)


@dataclass
class ContainerReplica:
    dn_id: str
    state: str = "OPEN"
    replica_index: int = 0  # 1-based for EC, 0 for Ratis
    block_count: int = 0
    used_bytes: int = 0


@dataclass
class ContainerInfo:
    id: int
    replication: ReplicationConfig
    pipeline: Optional[Pipeline]
    state: ContainerState = ContainerState.OPEN
    used_bytes: int = 0
    replicas: dict[str, ContainerReplica] = field(default_factory=dict)

    def replica_indexes_present(self) -> set[int]:
        return {
            r.replica_index
            for r in self.replicas.values()
            if r.state not in ("UNHEALTHY", "DELETED")
        }


class ContainerManager:
    def __init__(
        self,
        nodes: NodeManager,
        placement: PlacementPolicy,
        container_size: int = 5 * 1024 * 1024 * 1024,
        db_path=None,
    ):
        self.nodes = nodes
        self.placement = placement
        self.container_size = container_size
        self._containers: dict[int, ContainerInfo] = {}
        self._pipelines: dict[int, Pipeline] = {}
        self._next_cid = 1
        self._next_lid = 1
        # replicated pipeline-id floor (only used in HA mode; standalone
        # pipelines draw from the process-local allocator)
        self._next_pid = 1
        # SCM-HA commit-first id source (scm/sequence_id.py): when set,
        # container/block/pipeline ids are issued ONLY from ranges the
        # ring already committed (SequenceIdGenerator.java:52-84), so a
        # leadership hand-off can never re-issue an id this leader
        # exposed. When None (standalone / single process) the legacy
        # persisted counters are the source.
        self.id_source = None
        # open writable containers by replication-scheme string
        self._writable: dict[str, list[int]] = {}
        self._lock = threading.RLock()
        # SCM-HA hook: called with (row, counters) after every durable
        # state mutation; the leader's ReplicatedSCM ships these records
        # through the replicated log (the reference replicates leader
        # decisions the same way via @Replicate proxies: the marshalled
        # SCMRatisRequest carries the resulting container info, not the
        # nondeterministic placement computation — server-scm ha/
        # SCMHAInvocationHandler + SCMRatisRequest).
        self.mutation_listener = None
        # pipeline lifecycle hooks (RatisPipelineProvider / PipelineManager
        # close analog): the daemon wires these to issue join-pipeline /
        # leave-pipeline commands so member datanodes open and prune the
        # pipeline's raft group
        self.on_pipeline_created = None
        self.on_pipeline_closed = None
        # fired when a container enters CLOSING: the daemon queues
        # close-container commands so replicas actually close and report
        # CLOSED back (the reference's CloseContainerCommand round trip —
        # without it CLOSING would never converge to CLOSED)
        self.on_container_closing = None
        # optional persistence (reference: SCM metadata in RocksDB with
        # HA-safe SequenceIdGenerator; replicas rebuild from reports)
        self._db = None
        self._node_op_states: dict[str, str] = {}
        # StatefulService rows (balancer config/progress): persisted AND
        # replicated so services resume across restart and failover
        self._service_states: dict[str, dict] = {}
        if db_path is not None:
            from ozone_tpu.scm.scm_store import ScmStore

            self._db = ScmStore(db_path)
            self._recover()

    @staticmethod
    def _pipeline_from_row(row: dict) -> Pipeline:
        """Rebuild a persisted pipeline keeping its cluster-assigned id
        (datanode raft groups are named by it) and keep the allocator
        ahead of every restored id so new pipelines never collide."""
        from ozone_tpu.scm.pipeline import _pipeline_ids

        repl = ReplicationConfig.parse(row["replication"])
        kw = {}
        if row.get("pipeline_id") is not None:
            kw["id"] = int(row["pipeline_id"])
        p = Pipeline(repl, list(row["nodes"]), **kw)
        _pipeline_ids.advance_past(p.id)
        return p

    def _recover(self) -> None:
        state = self._db.load()
        for c in state["containers"]:
            repl = ReplicationConfig.parse(c["replication"])
            cstate = ContainerState(c["state"])
            pipe = self._pipelines.get(
                int(c["pipeline_id"])
                if c.get("pipeline_id") is not None else -1
            )
            if pipe is None:
                pipe = self._pipeline_from_row(c)
                # pipeline rows aren't persisted standalone: resurrect a
                # retired pipeline as CLOSED until some attached
                # container proves it still carries writes — otherwise
                # admin/recon views and datanode join-pipeline commands
                # would revive raft groups of retired pipelines
                pipe.state = PipelineState.CLOSED
                self._pipelines[pipe.id] = pipe
            if cstate in (ContainerState.OPEN, ContainerState.CLOSING):
                pipe.state = PipelineState.OPEN
            info = ContainerInfo(
                c["id"], repl, pipe,
                state=cstate,
                used_bytes=int(c["used_bytes"]),
            )
            self._containers[info.id] = info
            if info.state is ContainerState.OPEN:
                self._writable.setdefault(str(repl), []).append(info.id)
        self._next_cid = state["next_container_id"]
        self._next_lid = state["next_local_id"]
        self._next_pid = max(
            int(state.get("pipeline_floor", 1)),
            max((p.id for p in self._pipelines.values()), default=0) + 1,
        )
        self._node_op_states = dict(state.get("node_op_states", {}))
        self._service_states = dict(state.get("service_states", {}))

    def _row(self, c: ContainerInfo) -> dict:
        return {
            "id": c.id,
            "replication": str(c.replication),
            "nodes": c.pipeline.nodes if c.pipeline else [],
            "pipeline_id": c.pipeline.id if c.pipeline else None,
            "state": c.state.value,
            "used_bytes": c.used_bytes,
        }

    def _persist(self, c: ContainerInfo) -> None:
        row = self._row(c)
        counters = (self._next_cid, self._next_lid)
        if self._db is not None:
            self._db.save_container(row, counters=counters)
        if self.mutation_listener is not None:
            self.mutation_listener(row, counters)

    def apply_mutation(self, row: dict, counters: tuple[int, int]) -> None:
        """Follower-side deterministic apply of a leader mutation record
        (SCMStateMachine.applyTransaction analog): upsert the container row
        and advance the HA-safe id counters. Service-state rows (the
        StatefulService records) ride the same channel."""
        if "service" in row:
            with self._lock:
                self._service_states[row["service"]] = dict(row["state"])
                if self._db is not None:
                    self._db.save_service_state(row["service"],
                                                dict(row["state"]))
            return
        with self._lock:
            c = self._containers.get(int(row["id"]))
            if c is None:
                repl = ReplicationConfig.parse(row["replication"])
                pipe = self._pipeline_from_row(row)
                self._pipelines[pipe.id] = pipe
                c = ContainerInfo(int(row["id"]), repl, pipe)
                self._containers[c.id] = c
            c.state = ContainerState(row["state"])
            c.used_bytes = int(row["used_bytes"])
            # keep pipeline liveness consistent on every recovery path
            # (WAL replay, follower apply, snapshot install): a pipeline
            # is live iff some attached container still takes writes
            self._refresh_pipeline_state(c.pipeline)
            pool = self._writable.setdefault(str(c.replication), [])
            if c.state is ContainerState.OPEN:
                if c.id not in pool:
                    pool.append(c.id)
            elif c.id in pool:
                pool.remove(c.id)
            self._next_cid = max(self._next_cid, int(counters[0]))
            self._next_lid = max(self._next_lid, int(counters[1]))
            if self._db is not None:
                self._db.save_container(
                    row, counters=(self._next_cid, self._next_lid)
                )

    def _refresh_pipeline_state(self, pipe) -> None:
        live = any(
            cc.pipeline.id == pipe.id
            and cc.state in (ContainerState.OPEN, ContainerState.CLOSING)
            for cc in self._containers.values()
        )
        pipe.state = (PipelineState.OPEN if live
                      else PipelineState.CLOSED)

    def snapshot_state(self) -> dict:
        """Full durable-state dump for follower bootstrap
        (SCMSnapshotProvider checkpoint-tarball analog)."""
        with self._lock:
            return {
                "containers": [
                    self._row(c) for c in self._containers.values()
                ],
                "counters": [self._next_cid, self._next_lid],
                "pipeline_floor": self._next_pid,
                "service_states": {
                    k: dict(v) for k, v in self._service_states.items()
                },
            }

    def install_snapshot(self, snap: dict) -> None:
        """Replace-all install of a shipped checkpoint: containers absent
        from the snapshot are dropped (a deposed leader resyncing may hold
        phantom rows the quorum never accepted), then every row is
        upserted. Replica soft state for surviving containers is kept —
        it is rebuilt from heartbeats either way."""
        with self._lock:
            keep = {int(r["id"]) for r in snap["containers"]}
            for cid in [c for c in self._containers if c not in keep]:
                c = self._containers.pop(cid)
                if c.pipeline is not None:
                    self._pipelines.pop(c.pipeline.id, None)
            for pool in self._writable.values():
                pool[:] = [cid for cid in pool if cid in keep]
        # service rows are replace-all too: a stale local 'balancer'
        # record not present in the leader's checkpoint must die here,
        # or a bootstrapped node resumes a service the cluster stopped
        with self._lock:
            self._service_states = {}
            if self._db is not None:
                self._db.replace_service_states({})
        for row in snap["containers"]:
            self.apply_mutation(row, tuple(snap["counters"]))
        for name, state in snap.get("service_states", {}).items():
            self.apply_mutation({"service": name, "state": state},
                                tuple(snap["counters"]))
        with self._lock:
            self._next_cid = max(self._next_cid, int(snap["counters"][0]))
            self._next_lid = max(self._next_lid, int(snap["counters"][1]))
            self._next_pid = max(
                self._next_pid,
                int(snap.get("pipeline_floor", 1)),
                max((p.id for p in self._pipelines.values()), default=0)
                + 1,
            )

    # --------------------------------------------------------------- queries
    def get(self, container_id: int) -> ContainerInfo:
        return self._containers[container_id]

    def get_or_none(self, container_id: int) -> Optional[ContainerInfo]:
        return self._containers.get(container_id)

    def containers(self) -> list[ContainerInfo]:
        return list(self._containers.values())

    def pipelines(self) -> list[Pipeline]:
        return list(self._pipelines.values())

    # --------------------------------------------------------------- alloc
    def peek_id_floor(self, kind: str) -> int:
        """Current committed floor for an id kind — the leader reads it
        to compose an absolute range-reservation record."""
        with self._lock:
            return {"container": self._next_cid,
                    "block": self._next_lid,
                    "pipeline": self._next_pid}[kind]

    def reserve_id_range(self, kind: str, lo: int, hi: int):
        """Deterministic apply of a commit-first range reservation
        (SequenceIdGenerator.java allocateBatch analog). The record
        carries ABSOLUTE bounds so re-apply (log replay over an
        already-persisted store) is idempotent and every replica
        converges on the identical floor. A stale record (lo below the
        floor — the proposer raced an earlier reservation) is REJECTED
        by returning None, deterministically on every replica; the live
        proposer re-reads the floor and retries. NEVER emits a
        mutation-listener record — the reservation IS the replicated
        record."""
        lo, hi = int(lo), int(hi)
        if hi <= lo:
            raise ValueError(f"bad reservation [{lo}, {hi})")
        with self._lock:
            if kind == "container":
                if lo < self._next_cid:
                    return None
                self._next_cid = hi
            elif kind == "block":
                if lo < self._next_lid:
                    return None
                self._next_lid = hi
            elif kind == "pipeline":
                if lo < self._next_pid:
                    return None
                self._next_pid = hi
            else:
                raise ValueError(f"unknown id kind {kind!r}")
            if self._db is not None:
                self._db.save_counters(
                    (self._next_cid, self._next_lid),
                    pipeline_floor=self._next_pid,
                )
            return [lo, hi]

    def _issue_block_id(self) -> int:
        if self.id_source is not None:
            # commit-first: may block on a ring round-trip; called
            # OUTSIDE the container lock (apply needs that lock)
            return self.id_source.next("block")
        with self._lock:
            lid = self._next_lid
            self._next_lid += 1
            return lid

    def _create_pipeline(
        self, replication: ReplicationConfig, excluded: list[str],
        pipeline_id: int | None = None,
    ) -> Pipeline:
        chosen = self.placement.choose(replication.required_nodes, excluded)
        kw = {"id": pipeline_id} if pipeline_id is not None else {}
        p = Pipeline(replication, [n.dn_id for n in chosen], **kw)
        if pipeline_id is not None:
            # keep the process-local allocator ahead of ring-issued ids
            # so locally-constructed pipelines can't collide
            from ozone_tpu.scm.pipeline import _pipeline_ids

            _pipeline_ids.advance_past(pipeline_id)
        self._pipelines[p.id] = p
        if self.on_pipeline_created is not None:
            try:
                self.on_pipeline_created(p)
            except Exception:  # noqa: BLE001 - allocation must not fail
                log.exception("pipeline-created hook failed for %s", p.id)
        return p

    def _allocate_container(
        self, replication: ReplicationConfig, excluded: list[str],
        container_id: int | None = None, pipeline_id: int | None = None,
    ) -> ContainerInfo:
        pipe = self._create_pipeline(replication, excluded,
                                     pipeline_id=pipeline_id)
        if container_id is None:
            container_id = self._next_cid
            self._next_cid += 1
        c = ContainerInfo(container_id, replication, pipe)
        self._containers[c.id] = c
        # no _persist here: allocate_block always persists the final row
        # (used_bytes + issued local id) right after
        return c

    def allocate_block(
        self,
        replication: ReplicationConfig,
        block_size: int,
        excluded: Optional[list[str]] = None,
        excluded_containers: Optional[list[int]] = None,
    ) -> BlockGroup:
        """Find-or-create an open container on a healthy pipeline and issue
        a new block id in it (allocateBlock -> WritableContainerFactory).
        `excluded_containers` mirrors the reference ExcludeList's
        container ids: a client that just saw CONTAINER_CLOSED must not
        be handed the same container back before its report lands.

        HA mode (id_source set): every id is drawn from a quorum-
        committed range BEFORE it is exposed — the reference's
        commit-first SequenceIdGenerator model (BlockManagerImpl.java:188
        consumes batches reserved through Raft), which makes duplicate
        (container, local_id) issuance across a leadership hand-off
        impossible by construction. Reservations happen OUTSIDE the
        container lock: the ring's apply path takes that lock, so a
        holder must never wait on a commit."""
        excluded = excluded or []
        excluded_containers = set(excluded_containers or ())
        lid = self._issue_block_id()
        # (cid, pid, issue-epoch) pre-issued outside the container lock
        new_ids: Optional[tuple[int, int, int]] = None
        while True:
            with self._lock:
                key = str(replication)
                pool = self._writable.setdefault(key, [])
                for cid in list(pool):
                    c = self._containers.get(cid)
                    if c is None or c.state is not ContainerState.OPEN:
                        pool.remove(cid)
                        continue
                    if cid in excluded_containers:
                        continue
                    if any(n in excluded for n in c.pipeline.nodes):
                        continue
                    if c.used_bytes + block_size > self.container_size:
                        # full: close it (reference closes via
                        # close-threshold)
                        self.finalize_container(cid)
                        pool.remove(cid)
                        continue
                    c.used_bytes += block_size
                    self._persist(c)
                    if new_ids is not None and self.id_source is not None:
                        # speculative ids unused: back to the free list
                        # (never exposed, still unique-by-construction).
                        # The issue-time epoch makes the return a no-op
                        # when a step-down burned the batch meanwhile.
                        self.id_source.release("container", new_ids[0],
                                               epoch=new_ids[2])
                        self.id_source.release("pipeline", new_ids[1],
                                               epoch=new_ids[2])
                    return BlockGroup(
                        container_id=cid,
                        local_id=lid,
                        pipeline=c.pipeline,
                    )
                if self.id_source is None:
                    c = self._allocate_container(replication, excluded)
                elif new_ids is not None:
                    c = self._allocate_container(
                        replication, excluded,
                        container_id=new_ids[0], pipeline_id=new_ids[1])
                else:
                    c = None  # need ids: reserve outside the lock, retry
                if c is not None:
                    pool.append(c.id)
                    c.used_bytes += block_size
                    self._persist(c)
                    return BlockGroup(
                        container_id=c.id,
                        local_id=lid,
                        pipeline=c.pipeline,
                    )
            ep = self.id_source.epoch
            new_ids = (self.id_source.next("container"),
                       self.id_source.next("pipeline"), ep)

    # --------------------------------------------------------------- lifecycle
    def _close_pipeline(self, c: ContainerInfo) -> None:
        """A container leaving OPEN retires its (1:1) pipeline: writes
        stop, members may drop the raft group (reads never needed it)."""
        p = c.pipeline
        if p is None or p.state is PipelineState.CLOSED:
            return
        p.state = PipelineState.CLOSED
        self._pipelines.pop(p.id, None)
        if self.on_pipeline_closed is not None:
            try:
                self.on_pipeline_closed(p)
            except Exception:  # noqa: BLE001 - lifecycle must not fail
                log.exception("pipeline-closed hook failed for %s", p.id)

    def finalize_container(self, container_id: int) -> None:
        c = self._containers[container_id]
        if c.state is ContainerState.OPEN:
            c.state = ContainerState.CLOSING
            self._persist(c)
            # the pipeline stays live through CLOSING: a RATIS close is
            # ordered through the pipeline's raft ring AFTER in-flight
            # writes; the pipeline retires at mark_closed
            self._fire_container_closing(c)

    def _fire_container_closing(self, c: ContainerInfo) -> None:
        if self.on_container_closing is not None:
            try:
                self.on_container_closing(c)
            except Exception:  # noqa: BLE001 - lifecycle must not fail
                log.exception("container-closing hook failed for %s", c.id)

    def service_state(self, name: str) -> Optional[dict]:
        """Persisted state of a stateful background service (reference:
        StatefulServiceStateManager.readConfiguration)."""
        with self._lock:
            v = self._service_states.get(name)
            return dict(v) if v is not None else None

    def persist_service_state(self, name: str, state: dict) -> None:
        """Durably record + replicate a service's config/progress
        (StatefulServiceStateManager.saveConfiguration analog — the
        reference's ContainerBalancer persists via exactly that hook,
        ContainerBalancer.java:281)."""
        with self._lock:
            self._service_states[name] = dict(state)
            counters = (self._next_cid, self._next_lid)
            if self._db is not None:
                self._db.save_service_state(name, dict(state))
            if self.mutation_listener is not None:
                self.mutation_listener(
                    {"service": name, "state": dict(state)}, counters)

    def node_op_states(self) -> dict[str, str]:
        """Durable node operational states loaded at recovery."""
        return dict(self._node_op_states)

    def persist_node_op_state(self, dn_id: str, state: str) -> None:
        if state == "IN_SERVICE":
            self._node_op_states.pop(dn_id, None)
        else:
            self._node_op_states[dn_id] = state
        if self._db is not None:
            self._db.save_node_op_state(dn_id, state)

    def resend_closing(self) -> None:
        """Re-announce close for every CLOSING container (background
        sweep): close commands are fire-and-forget over in-memory queues,
        so an SCM restart or missed heartbeat must not leave a container
        CLOSING forever."""
        with self._lock:
            closing = [c for c in self._containers.values()
                       if c.state is ContainerState.CLOSING]
        for c in closing:
            self._fire_container_closing(c)

    def mark_closed(self, container_id: int) -> None:
        c = self._containers[container_id]
        c.state = ContainerState.CLOSED
        self._persist(c)
        self._close_pipeline(c)

    def delete_container(self, container_id: int) -> None:
        c = self._containers[container_id]
        c.state = ContainerState.DELETED
        self._persist(c)
        self._close_pipeline(c)

    # --------------------------------------------------------------- reports
    def process_container_report(self, dn_id: str, report: list[dict]) -> None:
        """Ingest a full container report (FCR) from a datanode heartbeat."""
        seen = set()
        for r in report:
            cid = int(r["container_id"])
            seen.add(cid)
            c = self._containers.get(cid)
            if c is None:
                # unknown container: track it with unknown replication
                continue
            c.replicas[dn_id] = ContainerReplica(
                dn_id=dn_id,
                state=r["state"],
                replica_index=int(r.get("replica_index", 0)),
                block_count=int(r.get("block_count", 0)),
                used_bytes=int(r.get("used_bytes", 0)),
            )
            if r["state"] == "UNHEALTHY" \
                    and c.state is ContainerState.OPEN:
                # an unhealthy replica of an OPEN container (reference
                # ICR -> close flow): stop allocating into it — writers
                # roll to a fresh container (allocate_block prunes the
                # non-OPEN entry from its pool) and the replication
                # manager repairs the poisoned replica once it closes
                log.warning("container %d has unhealthy replica on %s; "
                            "closing", cid, dn_id)
                with self._lock:
                    self.finalize_container(cid)
        # drop replicas this DN no longer reports
        for c in self._containers.values():
            if dn_id in c.replicas and c.id not in seen:
                del c.replicas[dn_id]

    def remove_replicas_of_node(self, dn_id: str) -> list[int]:
        """Node death: forget its replicas; return affected container ids."""
        affected = []
        for c in self._containers.values():
            if dn_id in c.replicas:
                del c.replicas[dn_id]
                affected.append(c.id)
        for p in self._pipelines.values():
            if dn_id in p.nodes and p.state is PipelineState.OPEN:
                p.state = PipelineState.CLOSED
        return affected
