"""Decommission / maintenance drain monitor.

Mirror of the reference's NodeDecommissionManager.java:60 +
DatanodeAdminMonitorImpl: a node entering DECOMMISSIONING stops receiving
new allocations (placement only picks IN_SERVICE nodes), the replication
manager re-protects its replicas (copying from the draining node where the
replica is still live), and the monitor flips the node to DECOMMISSIONED
once every container it held is fully replicated elsewhere.
"""

from __future__ import annotations

import logging

from ozone_tpu.scm.container_manager import ContainerManager
from ozone_tpu.scm.node_manager import (
    NodeManager,
    NodeOperationalState,
)
from ozone_tpu.scm.pipeline import ReplicationType
from ozone_tpu.scm.replication_manager import ECReplicaCount, ReplicationManager

log = logging.getLogger(__name__)


class DecommissionMonitor:
    def __init__(
        self,
        nodes: NodeManager,
        containers: ContainerManager,
        replication: ReplicationManager,
    ):
        self.nodes = nodes
        self.containers = containers
        self.replication = replication

    def start_decommission(self, dn_id: str) -> None:
        n = self.nodes.get(dn_id)
        if n is None:
            raise KeyError(dn_id)
        self.nodes.set_op_state(dn_id, NodeOperationalState.DECOMMISSIONING)
        log.info("decommission started for %s", dn_id)

    def start_maintenance(self, dn_id: str) -> None:
        self.nodes.set_op_state(dn_id, NodeOperationalState.IN_MAINTENANCE)

    def recommission(self, dn_id: str) -> None:
        self.nodes.set_op_state(dn_id, NodeOperationalState.IN_SERVICE)

    def _node_drained(self, dn_id: str) -> bool:
        """All containers with a replica on dn_id are fully redundant
        without it (the admin monitor's sufficientlyReplicated check)."""
        for c in self.containers.containers():
            if dn_id not in c.replicas:
                continue
            if c.replication.type is ReplicationType.EC:
                count = ECReplicaCount(c, self.nodes)
                if count.missing_indexes:
                    return False
            else:
                live = [
                    d
                    for d in c.replicas
                    if d != dn_id
                    and (n := self.nodes.get(d)) is not None
                    and n.op_state is NodeOperationalState.IN_SERVICE
                ]
                if len(live) < c.replication.factor:
                    return False
        return True

    def run_once(self) -> list[str]:
        """Check draining nodes; finalize the drained ones. Returns nodes
        finalized this tick."""
        done = []
        for n in self.nodes.nodes():
            if n.op_state is not NodeOperationalState.DECOMMISSIONING:
                continue
            if self._node_drained(n.dn_id):
                self.nodes.set_op_state(
                    n.dn_id, NodeOperationalState.DECOMMISSIONED
                )
                log.info("decommission of %s complete", n.dn_id)
                done.append(n.dn_id)
        return done
