"""SCM high availability: replicated mutation log + snapshot bootstrap.

Capability mirror of the reference's SCM-HA stack (server-scm ha/:
SCMHAManagerImpl wires a Ratis server whose SCMStateMachine applies
marshalled @Replicate invocations on every peer; SCMHADBTransactionBuffer
batches the resulting RocksDB writes; SCMSnapshotProvider +
InterSCMGrpcProtocolService bootstrap new followers from a checkpoint
tarball and then tail the log).

Design notes, TPU-build shape:
- The reference replicates *leader decisions*, not computations: the
  SCMRatisRequest carries the resulting container/pipeline info so apply
  is deterministic even though placement is randomized. We do the same —
  the replication unit is the durable mutation record ContainerManager
  already emits on every state change (container row + HA-safe id
  counters), shipped through the same durable JSONL WAL used by OM HA
  (om/ha.py:RequestLog).
- Soft state (node liveness, container replicas) is NOT replicated —
  exactly like the reference, where every SCM receives datanode
  heartbeats and rebuilds replica maps from full container reports.
- Failover is promote()-based single-leader replication rather than Raft
  elections (SURVEY.md §7: stage consensus behind the request/apply
  split); followers are warm byte-identical replicas.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Optional

from ozone_tpu.om.ha import NotLeaderError, RequestLog
from ozone_tpu.scm.scm import StorageContainerManager

log = logging.getLogger(__name__)


class ReplicatedSCM:
    """One SCM replica: the leader accepts mutating calls and ships each
    resulting durable mutation to followers; followers apply them onto
    their own managers (SCMStateMachine.applyTransaction analog)."""

    def __init__(
        self,
        scm: StorageContainerManager,
        log_path: Path,
        scm_id: str,
        is_leader: bool = False,
    ):
        self.scm = scm
        self.scm_id = scm_id
        self.is_leader = is_leader
        self.wal = RequestLog(log_path)
        self.applied_index = 0
        self.peers: list["ReplicatedSCM"] = []
        self._replaying = False
        scm.containers.mutation_listener = self._on_mutation
        self._recover()

    # ------------------------------------------------------------- recovery
    def _recover(self) -> None:
        self._replaying = True
        try:
            for e in self.wal.read_from(0):
                if "snapshot" in e:
                    # bootstrap checkpoint recorded in the WAL so restarts
                    # of a bootstrapped follower recover the full state
                    self.scm.containers.install_snapshot(e["snapshot"])
                else:
                    self.scm.containers.apply_mutation(
                        e["row"], tuple(e["counters"])
                    )
                self.applied_index = e["index"]
        finally:
            self._replaying = False

    # ------------------------------------------------------------- leader
    def _on_mutation(self, row: dict, counters: tuple[int, int]) -> None:
        """ContainerManager hook: on the leader, every durable mutation is
        appended to the WAL and pushed to followers synchronously (the
        reference's Ratis write happens *before* apply; we hook after —
        equivalent durability because the record is also in the local
        sqlite store, and replay converges via upsert)."""
        if self._replaying or not self.is_leader:
            return
        entry = {
            # applied_index, not WAL line count: a bootstrapped follower's
            # WAL holds one snapshot entry standing in for many indexes
            "index": self.applied_index + 1,
            "row": row,
            "counters": list(counters),
        }
        self.wal.append(entry)
        self.applied_index = entry["index"]
        for peer in self.peers:
            try:
                peer.replicate(entry)
            except Exception:
                log.exception("scm replication to %s failed", peer.scm_id)

    def check_leader(self) -> None:
        if not self.is_leader:
            raise NotLeaderError(self.scm_id)

    def submit(self, method: str, *args: Any, **kw: Any) -> Any:
        """Leader-gated mutating entry point (SCMHAInvocationHandler
        analog): clients/om route allocate_block, delete_blocks,
        decommission, ... through here so followers reject writes."""
        self.check_leader()
        return getattr(self.scm, method)(*args, **kw)

    # ------------------------------------------------------------- follower
    def replicate(self, entry: dict) -> None:
        if entry["index"] <= self.applied_index:
            return
        if entry["index"] != self.applied_index + 1:
            self.catch_up()
            if entry["index"] <= self.applied_index:
                return
            if entry["index"] != self.applied_index + 1:
                # gap we could not close (leader unreachable): stay behind
                # rather than skip entries; the next catch_up re-fetches
                log.warning(
                    "scm %s dropping out-of-order entry %d (applied %d)",
                    self.scm_id, entry["index"], self.applied_index,
                )
                return
        self._replaying = True
        try:
            self.wal.append(entry)
            self.scm.containers.apply_mutation(
                entry["row"], tuple(entry["counters"])
            )
            self.applied_index = entry["index"]
        finally:
            self._replaying = False

    def catch_up(self) -> None:
        leader = next((p for p in self.peers if p.is_leader), None)
        if leader is None:
            return
        self._replaying = True
        try:
            # scan from 0 and filter by index: WAL line offsets are not
            # indexes once a snapshot entry (standing in for many indexes)
            # is present in the leader's log
            for e in leader.wal.read_from(0):
                if e["index"] <= self.applied_index:
                    continue
                self.wal.append(e)
                if "snapshot" in e:
                    self.scm.containers.install_snapshot(e["snapshot"])
                else:
                    self.scm.containers.apply_mutation(
                        e["row"], tuple(e["counters"])
                    )
                self.applied_index = e["index"]
        finally:
            self._replaying = False

    # ------------------------------------------------------------- bootstrap
    def bootstrap_from(self, leader: "ReplicatedSCM") -> None:
        """New-follower bootstrap: install the leader's checkpoint, then
        tail its log (SCMSnapshotProvider + InterSCMGrpcProtocolService)."""
        snap = leader.scm.containers.snapshot_state()
        self._replaying = True
        try:
            self.scm.containers.install_snapshot(snap)
        finally:
            self._replaying = False
        self.applied_index = leader.applied_index
        # record the checkpoint durably so restart recovery and post-
        # promotion index assignment both see the bootstrapped state
        self.wal.append({"index": self.applied_index, "snapshot": snap})
        if self not in leader.peers:
            leader.peers.append(self)
        if leader not in self.peers:
            self.peers.append(leader)

    # ------------------------------------------------------------- failover
    def promote(self) -> None:
        self.catch_up()
        for p in self.peers:
            p.is_leader = False
        self.is_leader = True
        log.info(
            "scm %s promoted to leader at index %d",
            self.scm_id,
            self.applied_index,
        )


class SCMFailoverProxy:
    """Client/OM-side failover across SCM replicas (the reference's
    SCMBlockLocationFailoverProxyProvider): tries the known leader,
    rotates on NotLeaderError or connection failure."""

    def __init__(self, replicas: list[ReplicatedSCM]):
        self.replicas = replicas
        self._leader_idx = 0

    def submit(self, method: str, *args: Any, **kw: Any) -> Any:
        last: Optional[Exception] = None
        n = len(self.replicas)
        for attempt in range(n):
            idx = (self._leader_idx + attempt) % n
            try:
                result = self.replicas[idx].submit(method, *args, **kw)
                self._leader_idx = idx
                return result
            except (NotLeaderError, ConnectionError, OSError) as e:
                last = e
        raise RuntimeError(f"no SCM leader reachable: {last}")
