"""SCM high availability: replicated mutation log + snapshot bootstrap.

Capability mirror of the reference's SCM-HA stack (server-scm ha/:
SCMHAManagerImpl wires a Ratis server whose SCMStateMachine applies
marshalled @Replicate invocations on every peer; SCMHADBTransactionBuffer
batches the resulting RocksDB writes; SCMSnapshotProvider +
InterSCMGrpcProtocolService bootstrap new followers from a checkpoint
tarball and then tail the log).

Design notes, TPU-build shape:
- The reference replicates *leader decisions*, not computations: the
  SCMRatisRequest carries the resulting container/pipeline info so apply
  is deterministic even though placement is randomized. We do the same —
  the replication unit is the durable mutation record ContainerManager
  already emits on every state change (container row + HA-safe id
  counters), shipped through the same durable JSONL WAL used by OM HA
  (om/ha.py:RequestLog).
- Soft state (node liveness, container replicas) is NOT replicated —
  exactly like the reference, where every SCM receives datanode
  heartbeats and rebuilds replica maps from full container reports.
- Failover is promote()-based single-leader replication rather than Raft
  elections (SURVEY.md §7: stage consensus behind the request/apply
  split); followers are warm byte-identical replicas.
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from typing import Any, Optional

from ozone_tpu.om.ha import NotLeaderError, RequestLog
from ozone_tpu.scm.scm import StorageContainerManager

log = logging.getLogger(__name__)


class ReplicatedSCM:
    """One SCM replica: the leader accepts mutating calls and ships each
    resulting durable mutation to followers; followers apply them onto
    their own managers (SCMStateMachine.applyTransaction analog)."""

    def __init__(
        self,
        scm: StorageContainerManager,
        log_path: Path,
        scm_id: str,
        is_leader: bool = False,
    ):
        self.scm = scm
        self.scm_id = scm_id
        self.is_leader = is_leader
        self.wal = RequestLog(log_path)
        self.applied_index = 0
        self.peers: list["ReplicatedSCM"] = []
        self._replaying = False
        scm.containers.mutation_listener = self._on_mutation
        self._recover()

    # ------------------------------------------------------------- recovery
    def _recover(self) -> None:
        self._replaying = True
        try:
            for e in self.wal.read_from(0):
                if "snapshot" in e:
                    # bootstrap checkpoint recorded in the WAL so restarts
                    # of a bootstrapped follower recover the full state
                    self.scm.containers.install_snapshot(e["snapshot"])
                else:
                    self.scm.containers.apply_mutation(
                        e["row"], tuple(e["counters"])
                    )
                self.applied_index = e["index"]
        finally:
            self._replaying = False

    # ------------------------------------------------------------- leader
    def _on_mutation(self, row: dict, counters: tuple[int, int]) -> None:
        """ContainerManager hook: on the leader, every durable mutation is
        appended to the WAL and pushed to followers synchronously (the
        reference's Ratis write happens *before* apply; we hook after —
        equivalent durability because the record is also in the local
        sqlite store, and replay converges via upsert)."""
        if self._replaying or not self.is_leader:
            return
        entry = {
            # applied_index, not WAL line count: a bootstrapped follower's
            # WAL holds one snapshot entry standing in for many indexes
            "index": self.applied_index + 1,
            "row": row,
            "counters": list(counters),
        }
        self.wal.append(entry)
        self.applied_index = entry["index"]
        for peer in self.peers:
            try:
                peer.replicate(entry)
            except Exception:
                log.exception("scm replication to %s failed", peer.scm_id)

    def check_leader(self) -> None:
        if not self.is_leader:
            raise NotLeaderError(self.scm_id)

    def submit(self, method: str, *args: Any, **kw: Any) -> Any:
        """Leader-gated mutating entry point (SCMHAInvocationHandler
        analog): clients/om route allocate_block, delete_blocks,
        decommission, ... through here so followers reject writes."""
        self.check_leader()
        return getattr(self.scm, method)(*args, **kw)

    # ------------------------------------------------------------- follower
    def replicate(self, entry: dict) -> None:
        if entry["index"] <= self.applied_index:
            return
        if entry["index"] != self.applied_index + 1:
            self.catch_up()
            if entry["index"] <= self.applied_index:
                return
            if entry["index"] != self.applied_index + 1:
                # gap we could not close (leader unreachable): stay behind
                # rather than skip entries; the next catch_up re-fetches
                log.warning(
                    "scm %s dropping out-of-order entry %d (applied %d)",
                    self.scm_id, entry["index"], self.applied_index,
                )
                return
        self._replaying = True
        try:
            self.wal.append(entry)
            self.scm.containers.apply_mutation(
                entry["row"], tuple(entry["counters"])
            )
            self.applied_index = entry["index"]
        finally:
            self._replaying = False

    def catch_up(self) -> None:
        leader = next((p for p in self.peers if p.is_leader), None)
        if leader is None:
            return
        self._replaying = True
        try:
            # scan from 0 and filter by index: WAL line offsets are not
            # indexes once a snapshot entry (standing in for many indexes)
            # is present in the leader's log
            for e in leader.wal.read_from(0):
                if e["index"] <= self.applied_index:
                    continue
                self.wal.append(e)
                if "snapshot" in e:
                    self.scm.containers.install_snapshot(e["snapshot"])
                else:
                    self.scm.containers.apply_mutation(
                        e["row"], tuple(e["counters"])
                    )
                self.applied_index = e["index"]
        finally:
            self._replaying = False

    # ------------------------------------------------------------- bootstrap
    def bootstrap_from(self, leader: "ReplicatedSCM") -> None:
        """New-follower bootstrap: install the leader's checkpoint, then
        tail its log (SCMSnapshotProvider + InterSCMGrpcProtocolService)."""
        snap = leader.scm.containers.snapshot_state()
        self._replaying = True
        try:
            self.scm.containers.install_snapshot(snap)
        finally:
            self._replaying = False
        self.applied_index = leader.applied_index
        # record the checkpoint durably so restart recovery and post-
        # promotion index assignment both see the bootstrapped state
        self.wal.append({"index": self.applied_index, "snapshot": snap})
        if self not in leader.peers:
            leader.peers.append(self)
        if leader not in self.peers:
            self.peers.append(leader)

    # ------------------------------------------------------------- failover
    def promote(self) -> None:
        self.catch_up()
        for p in self.peers:
            p.is_leader = False
        self.is_leader = True
        log.info(
            "scm %s promoted to leader at index %d",
            self.scm_id,
            self.applied_index,
        )


class RaftSCM:
    """SCM replica on quorum consensus — the full SCMRatisServerImpl +
    SCMStateMachine analog (server-scm ha/): elections, quorum-committed
    mutation log, snapshot bootstrap for lagging followers.

    Replication unit matches the reference's design (and ReplicatedSCM
    above): the leader replicates *decision records* — durable container
    mutations + HA-safe id counters — not the computations that produced
    them, so apply is deterministic despite randomized placement. Soft
    state (node liveness, replica maps) is rebuilt from heartbeats on
    every SCM, exactly like the reference.

    Concurrency contract (lock order is raft-node -> container-manager,
    never the reverse):
    - The ContainerManager mutation hook runs under the container lock;
      it only *enqueues* the decision record. A single dispatcher thread
      proposes records through raft in mutation order, so client threads
      never touch raft state while holding the container lock.
    - Records the leader enqueued are already applied to its own state
      (the mutation produced them), so the local commit apply skips them
      by record id; followers (and log replay after a restart, when the
      in-flight set is empty) apply every record.
    - submit() acks the client only after the records its call produced
      are quorum-committed — the same client-visible durability as the
      reference, where the Ratis write precedes the response.
    - If leadership is lost with enqueued-but-uncommitted records, this
      replica's state has effects the quorum never accepted; it resyncs
      by fetching the new leader's full committed state (fetch_state)
      before serving again.
    """

    def __init__(
        self,
        scm: StorageContainerManager,
        raft_dir: Path,
        scm_id: str,
        peer_ids: list[str],
        transport=None,
        config=None,
        ack_timeout_s: float = 30.0,
    ):
        import queue as _queue

        from ozone_tpu.consensus.raft import RaftConfig, RaftNode

        self.scm = scm
        self.scm_id = scm_id
        self.ack_timeout_s = ack_timeout_s
        self._queue: "_queue.Queue" = _queue.Queue()  # ozlint: allow[bounded-queue] -- callers block on _ack_cv until their record commits (ack_timeout_s bounded), so depth is capped by the ack window, not open-ended
        self._inflight: set[str] = set()
        self._seq = 0
        self._committed_seq = 0
        self._ack_cv = threading.Condition()
        self._needs_resync = False
        self._stop = threading.Event()
        self.node = RaftNode(
            scm_id,
            peer_ids,
            Path(raft_dir),
            apply_fn=self._apply,
            snapshot_fn=scm.containers.snapshot_state,
            restore_fn=self._restore,
            config=config or RaftConfig(),
            transport=transport,
            on_step_down=self._on_step_down,
        )
        scm.containers.mutation_listener = self._on_mutation
        # commit-first id issuance (SequenceIdGenerator.java:52-84): the
        # container manager draws container/block/pipeline ids only from
        # ranges this ring already committed; a hand-off invalidates the
        # local batch, so two terms can never issue the same id — the
        # round-3 acked-data corruption (KNOWN_ISSUES.md) is impossible
        # by construction
        from ozone_tpu.scm.sequence_id import SequenceIdGenerator

        self.ids = SequenceIdGenerator(self._reserve_ids)
        scm.containers.id_source = self.ids
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, daemon=True,
            name=f"scm-ha-dispatch-{scm_id}")
        self._dispatcher.start()

    def _reserve_ids(self, kind: str, count: int) -> tuple[int, int]:
        """Propose an absolute range reservation and wait for the quorum
        commit; the applied result is the reserved [lo, hi). The
        is_ready_leader gate matters: a just-elected leader that has not
        applied the committed prefix could read a stale floor and compose
        a range overlapping one already exposed — readiness (plus the
        deterministic apply-side rejection) closes that window. Raises
        NotRaftLeaderError when this node cannot commit — the caller's
        allocation fails WITHOUT exposing any id, and the client retries
        on the real leader."""
        from ozone_tpu.consensus.raft import NotRaftLeaderError

        for _ in range(8):
            if not self.node.is_ready_leader:
                raise NotRaftLeaderError(self.scm_id, self.node.leader_hint)
            lo = self.scm.containers.peek_id_floor(kind)
            result = self.node.propose(
                {"seq_reserve": {"kind": kind, "lo": lo,
                                 "hi": lo + int(count)}},
                timeout=self.ack_timeout_s,
            )
            if isinstance(result, Exception):
                raise result
            if result is not None:
                lo, hi = result
                return int(lo), int(hi)
            # stale floor (an earlier in-log reservation intervened):
            # re-read and retry
        raise TimeoutError(
            f"id reservation for {kind!r} kept racing the floor")

    # ------------------------------------------------------------- leader
    def _on_mutation(self, row: dict, counters: tuple[int, int]) -> None:
        """ContainerManager hook (runs under the container lock): enqueue
        the decision record. Enqueue order == mutation order because the
        hook fires inside the mutating critical section."""
        if not self.node.is_leader:
            return
        with self._ack_cv:
            self._seq += 1
            rec_id = f"{self.scm_id}:{self._seq}"
            self._inflight.add(rec_id)
        self._queue.put(
            {"id": rec_id, "seq": self._seq, "row": row,
             "counters": list(counters)}
        )

    def _dispatch_loop(self) -> None:
        import queue as _queue

        from ozone_tpu.consensus.raft import NotRaftLeaderError

        while not self._stop.is_set():
            try:
                rec = self._queue.get(timeout=0.1)
            except _queue.Empty:
                self._maybe_resync()
                continue
            while not self._stop.is_set():
                try:
                    self.node.propose(
                        {k: rec[k] for k in ("id", "row", "counters")},
                        timeout=5.0,
                    )
                    with self._ack_cv:
                        self._committed_seq = rec["seq"]
                        self._ack_cv.notify_all()
                    break
                except NotRaftLeaderError:
                    # effects of this record exist locally but were never
                    # accepted by the quorum: flag for state resync and
                    # fail any waiting submits
                    with self._ack_cv:
                        self._needs_resync = True
                        self._committed_seq = rec["seq"]
                        self._ack_cv.notify_all()
                    break
                except TimeoutError:  # ozlint: allow[error-swallowing] -- keep retrying the quorum commit while still leader
                    continue

    def _maybe_resync(self) -> None:
        import queue as _queue

        if not self._needs_resync or self.node.is_leader:
            return
        hint = self.node.leader_hint
        if not hint or hint == self.scm_id:
            return
        # drop queued records that will never replicate (their effects are
        # about to be overwritten by the leader's committed state)
        try:
            while True:
                rec = self._queue.get_nowait()
                with self._ack_cv:
                    self._committed_seq = max(self._committed_seq,
                                              rec["seq"])
                    self._ack_cv.notify_all()
        except _queue.Empty:  # ozlint: allow[error-swallowing] -- Empty terminates the drain loop by design
            pass
        try:
            if self.node.fetch_state_from(hint):
                with self._ack_cv:
                    self._needs_resync = False
                    self._inflight.clear()
                # state replaced wholesale: any leftover local batch is
                # from a leadership the quorum moved past
                self.ids.invalidate()
                log.info("scm %s resynced from leader %s", self.scm_id, hint)
        except Exception as e:
            log.debug("scm %s resync attempt failed: %s", self.scm_id, e)

    def _on_step_down(self) -> None:
        """Raft callback (node lock held — flags only): unreplicated local
        effects mean divergence; resync from the new leader. The id
        batches die with the leadership (invalidateBatch analog) — their
        unissued tails are burned, never re-reserved."""
        self.ids.invalidate()
        with self._ack_cv:
            if self._inflight or not self._queue.empty():
                self._needs_resync = True
            self._ack_cv.notify_all()

    # ------------------------------------------------------------- apply
    def _apply(self, data: dict):
        if "seq_reserve" in data:
            r = data["seq_reserve"]
            return self.scm.containers.reserve_id_range(
                r["kind"], int(r["lo"]), int(r["hi"]))
        rec_id = data.get("id")
        if rec_id is not None:
            with self._ack_cv:
                if rec_id in self._inflight:
                    # our own record: the mutation that produced it
                    # already updated local state
                    self._inflight.discard(rec_id)
                    return
        self.scm.containers.apply_mutation(
            data["row"], tuple(data["counters"])
        )

    def _restore(self, snap: dict) -> None:
        self.scm.containers.install_snapshot(snap)

    @property
    def is_leader(self) -> bool:
        return self.node.is_leader

    # ------------------------------------------------------------- serving
    def submit(self, method: str, *args: Any, **kw: Any) -> Any:
        """Leader-gated mutating call; returns after every decision record
        the call produced is quorum-committed."""
        from ozone_tpu.consensus.raft import NotRaftLeaderError

        if not self.node.is_leader:
            raise NotRaftLeaderError(self.scm_id, self.node.leader_hint)
        result = getattr(self.scm, method)(*args, **kw)
        self._await_records()
        return result

    def _await_records(self) -> None:
        """Block until every decision record enqueued so far is
        quorum-committed (the ack tail shared with the combined
        metadata ring's OM submits)."""
        from ozone_tpu.consensus.raft import NotRaftLeaderError

        deadline = time.monotonic() + self.ack_timeout_s
        with self._ack_cv:
            target = self._seq
            while self._committed_seq < target:
                if self._needs_resync or not self.node.is_leader:
                    raise NotRaftLeaderError(self.scm_id,
                                             self.node.leader_hint)
                left = deadline - time.monotonic()
                if left <= 0:
                    raise TimeoutError(
                        "scm mutation not committed within "
                        f"{self.ack_timeout_s}s")
                self._ack_cv.wait(timeout=min(left, 0.05))

    def start(self) -> None:
        self.node.start_timers()

    def stop(self) -> None:
        self._stop.set()
        self.node.stop()
        self._dispatcher.join(timeout=1.0)


class SCMFailoverProxy:
    """Client/OM-side failover across SCM replicas (the reference's
    SCMBlockLocationFailoverProxyProvider): tries the known leader,
    rotates on NotLeaderError or connection failure."""

    def __init__(self, replicas: list[ReplicatedSCM]):
        self.replicas = replicas
        self._leader_idx = 0

    def submit(self, method: str, *args: Any, **kw: Any) -> Any:
        from ozone_tpu.consensus.raft import NotRaftLeaderError

        last: Optional[Exception] = None
        n = len(self.replicas)
        for attempt in range(n):
            idx = (self._leader_idx + attempt) % n
            try:
                result = self.replicas[idx].submit(method, *args, **kw)
                self._leader_idx = idx
                return result
            except (NotLeaderError, NotRaftLeaderError, TimeoutError,
                    ConnectionError, OSError) as e:
                last = e
        raise RuntimeError(f"no SCM leader reachable: {last}")
