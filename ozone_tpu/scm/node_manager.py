"""SCM node management: registration, heartbeats, liveness state machine,
per-node command queues.

Mirrors server-scm node handling (SCMNodeManager.java:115 register +
processHeartbeat with piggybacked command delivery; NodeStateManager's
HEALTHY -> STALE -> DEAD transitions driven by heartbeat age, with handler
events on transition — StaleNodeHandler/DeadNodeHandler).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Optional

from ozone_tpu.utils.events import EventQueue

log = logging.getLogger(__name__)


class NodeState(Enum):
    HEALTHY = "HEALTHY"
    STALE = "STALE"
    DEAD = "DEAD"


class NodeOperationalState(Enum):
    IN_SERVICE = "IN_SERVICE"
    DECOMMISSIONING = "DECOMMISSIONING"
    DECOMMISSIONED = "DECOMMISSIONED"
    IN_MAINTENANCE = "IN_MAINTENANCE"


# event topics
STALE_NODE = "scm.stale_node"
DEAD_NODE = "scm.dead_node"
NEW_NODE = "scm.new_node"
HEALTHY_READBACK = "scm.node_healthy_again"


@dataclass
class NodeInfo:
    dn_id: str
    rack: str = "/default-rack"
    capacity_bytes: int = 0
    used_bytes: int = 0
    last_heartbeat: float = field(default_factory=time.monotonic)
    state: NodeState = NodeState.HEALTHY
    layout_version: int = -1  # -1: not reported yet
    op_state: NodeOperationalState = NodeOperationalState.IN_SERVICE
    #: healthy-disk count from heartbeats (-1: not reported). 0 means
    #: the node is alive but storage-dead — never a placement target
    #: (the reference's failed-volume / zero-remaining SCMNodeStat case)
    healthy_volumes: int = -1


class NodeManager:
    def __init__(
        self,
        events: Optional[EventQueue] = None,
        stale_after_s: float = 9.0,
        dead_after_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.events = events or EventQueue()
        self.stale_after = stale_after_s
        self.dead_after = dead_after_s
        self.clock = clock
        self._nodes: dict[str, NodeInfo] = {}
        self._commands: dict[str, list[Any]] = {}
        self._lock = threading.Lock()
        # SCM-durable op states (seeded from the SCM store at startup;
        # authoritative over the DN's own echo) + persistence hook
        self._seeded_op: dict[str, str] = {}
        self.on_op_state_change = None

    # ---------------------------------------------------------------- members
    def register(self, dn_id: str, rack: str = "/default-rack",
                 capacity_bytes: int = 0,
                 op_state: Optional[str] = None) -> None:
        # events publish OUTSIDE the lock: handlers take other managers'
        # locks (e.g. ContainerManager), and those managers' hooks call
        # back into queue_command — publishing under the lock would make
        # the A->B / B->A deadlock reachable
        is_new = False
        with self._lock:
            if dn_id not in self._nodes:
                n = NodeInfo(dn_id, rack, capacity_bytes,
                             last_heartbeat=self.clock())
                # adopt an operational state on (re)registration: the
                # SCM's own durable record wins; the node's persisted
                # echo covers an SCM that lost its store (the reference
                # adopts persistedOpState at register the same way)
                adopted = self._seeded_op.get(dn_id) or op_state
                if adopted:
                    try:
                        n.op_state = NodeOperationalState(adopted)
                    except ValueError:
                        log.warning(
                            "%s reported unknown op state %r; treating "
                            "as IN_SERVICE", dn_id, adopted)
                self._nodes[dn_id] = n
                self._commands.setdefault(dn_id, [])
                is_new = True
            else:
                n = self._nodes[dn_id]
                n.last_heartbeat = self.clock()
                # re-registration refreshes what the node reports (the
                # reference re-reads StorageLocationReport): a restart
                # after disk loss/resize must not leave stale capacity
                # feeding the usage columns and capacity placement
                # 0 is a real report (all disks gone/unreadable), not
                # an omission: register() callers that don't track
                # capacity pass the default 0 only at CREATE time, and
                # a restart after disk loss must not keep stale numbers
                n.capacity_bytes = capacity_bytes
                n.rack = rack
        if is_new:
            self.events.publish(NEW_NODE, dn_id)

    def process_heartbeat(self, dn_id: str, used_bytes: int = 0) -> list[Any]:
        """Record a heartbeat; return queued commands for the node
        (SCM commands ride heartbeat responses in the reference)."""
        recovered = False
        with self._lock:
            n = self._nodes.get(dn_id)
            if n is None:
                # unknown node: ask it to re-register
                return [{"type": "register"}]
            n.last_heartbeat = self.clock()
            n.used_bytes = used_bytes
            if n.state is not NodeState.HEALTHY:
                n.state = NodeState.HEALTHY
                recovered = True
            cmds, self._commands[dn_id] = self._commands.get(dn_id, []), []
        if recovered:
            self.events.publish(HEALTHY_READBACK, dn_id)
        return cmds

    def check_liveness(self) -> None:
        """Periodic sweep advancing HEALTHY->STALE->DEAD by heartbeat age."""
        now = self.clock()
        transitions: list[tuple[str, str]] = []
        with self._lock:
            for n in self._nodes.values():
                age = now - n.last_heartbeat
                if age > self.dead_after and n.state is not NodeState.DEAD:
                    n.state = NodeState.DEAD
                    transitions.append((DEAD_NODE, n.dn_id))
                elif (
                    self.stale_after < age <= self.dead_after
                    and n.state is NodeState.HEALTHY
                ):
                    n.state = NodeState.STALE
                    transitions.append((STALE_NODE, n.dn_id))
        for topic, dn_id in transitions:
            self.events.publish(topic, dn_id)

    # ---------------------------------------------------------------- queries
    def get(self, dn_id: str) -> Optional[NodeInfo]:
        return self._nodes.get(dn_id)

    def nodes(self, state: Optional[NodeState] = None) -> list[NodeInfo]:
        out = list(self._nodes.values())
        return [n for n in out if state is None or n.state is state]

    def healthy_in_service(self) -> list[NodeInfo]:
        return [
            n
            for n in self._nodes.values()
            if n.state is NodeState.HEALTHY
            and n.op_state is NodeOperationalState.IN_SERVICE
        ]

    def node_count(self) -> int:
        return len(self._nodes)

    # ---------------------------------------------------------------- cmds
    def queue_command(self, dn_id: str, command: Any) -> None:
        with self._lock:
            self._commands.setdefault(dn_id, []).append(command)

    def pending_commands(self, dn_id: str) -> int:
        return len(self._commands.get(dn_id, []))

    # ---------------------------------------------------------------- admin
    def seed_op_states(self, states: dict[str, str]) -> None:
        """Install the SCM store's durable op-state records (applied to
        nodes as they register)."""
        with self._lock:
            self._seeded_op.update(states)

    def set_op_state(self, dn_id: str, state: NodeOperationalState) -> None:
        n = self._nodes[dn_id]
        n.op_state = state
        with self._lock:
            if state is NodeOperationalState.IN_SERVICE:
                self._seeded_op.pop(dn_id, None)
            else:
                self._seeded_op[dn_id] = state.value
        if self.on_op_state_change is not None:
            try:
                self.on_op_state_change(dn_id, state.value)
            except Exception:  # noqa: BLE001 - persistence must not fail ops
                log.exception("op-state persistence failed for %s", dn_id)
        # tell the datanode so it persists the state and reports it back
        # at (re)registration — covers an SCM that lost its store
        # (the reference's SetNodeOperationalStateCommand +
        # persistedOpState round trip)
        self.queue_command(dn_id, {
            "type": "set-op-state", "op_state": state.value,
        })
