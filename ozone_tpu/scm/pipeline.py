"""Pipeline and replication-config model.

Mirrors the reference's hdds/client ReplicationConfig hierarchy
(RatisReplicationConfig / ECReplicationConfig, hdds/client/
ECReplicationConfig.java) and the SCM pipeline object (hdds Pipeline:
a set of datanodes carrying one replication scheme; for EC, each node is
bound to a replica index 1..d+p — ECPipelineProvider.java:45).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ozone_tpu.codec.api import CoderOptions


class ReplicationType(Enum):
    STANDALONE = "STANDALONE"
    RATIS = "RATIS"
    EC = "EC"


@dataclass(frozen=True)
class ReplicationConfig:
    """Replication scheme of a bucket/key/container."""

    type: ReplicationType
    factor: int = 1  # RATIS/STANDALONE replica count
    ec: Optional[CoderOptions] = None

    @classmethod
    def ratis(cls, factor: int = 3) -> "ReplicationConfig":
        return cls(ReplicationType.RATIS, factor=factor)

    @classmethod
    def standalone(cls) -> "ReplicationConfig":
        return cls(ReplicationType.STANDALONE, factor=1)

    @classmethod
    def from_ec(cls, ec: CoderOptions) -> "ReplicationConfig":
        return cls(ReplicationType.EC, factor=ec.all_units, ec=ec)

    @classmethod
    def parse(cls, s: str) -> "ReplicationConfig":
        """Parse "RATIS/THREE", "RATIS/1", "rs-6-3-1024k" style strings."""
        s = s.strip()
        up = s.upper()
        if up.startswith("RATIS") or up.startswith("STANDALONE"):
            t = ReplicationType.RATIS if up.startswith("RATIS") else \
                ReplicationType.STANDALONE
            factor = 3
            if "/" in s:
                f = s.split("/")[1].upper()
                factor = {"ONE": 1, "THREE": 3}.get(f) or int(f)
            return cls(t, factor=factor)
        return cls.from_ec(CoderOptions.parse(s))

    @property
    def required_nodes(self) -> int:
        return self.ec.all_units if self.ec else self.factor

    def __str__(self) -> str:
        if self.type is ReplicationType.EC:
            return str(self.ec)
        return f"{self.type.value}/{self.factor}"


class PipelineState(Enum):
    ALLOCATED = "ALLOCATED"
    OPEN = "OPEN"
    DORMANT = "DORMANT"
    CLOSED = "CLOSED"


class _PipelineIdAllocator:
    """Monotonic pipeline-id source that can be advanced past persisted
    ids on recovery — a regenerated id colliding with one a datanode
    still serves a raft group under would silently mis-address writes."""

    def __init__(self):
        self._last = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._last += 1
            return self._last

    def advance_past(self, pipeline_id: int) -> None:
        with self._lock:
            self._last = max(self._last, int(pipeline_id))


_pipeline_ids = _PipelineIdAllocator()


@dataclass
class Pipeline:
    """An ordered set of datanodes carrying one replication scheme.

    For EC pipelines, node i (0-based) holds replica index i+1 — data units
    first, then parity, matching ECBlockOutputStreamEntry's fan-out
    (replicationIndex 1..d+p)."""

    replication: ReplicationConfig
    nodes: list[str]  # datanode ids, ordered
    id: int = field(default_factory=_pipeline_ids.next)
    state: PipelineState = PipelineState.OPEN

    def __post_init__(self):
        if len(self.nodes) != self.replication.required_nodes:
            raise ValueError(
                f"pipeline needs {self.replication.required_nodes} nodes, "
                f"got {len(self.nodes)}"
            )

    def replica_index(self, dn_id: str) -> int:
        """1-based replica index of a node (EC), mirroring
        Pipeline.getReplicaIndex in the reference."""
        return self.nodes.index(dn_id) + 1

    def node_for_index(self, replica_index: int) -> str:
        return self.nodes[replica_index - 1]
