"""Placement policies: rack-scatter (EC), rack-aware, capacity, random.

Mirrors server-scm container/placement (SCMContainerPlacementRackScatter —
EC spreads d+p across as many racks as possible; ...RackAware,
...Capacity, ...Random; SCMCommonPlacementPolicy validation).
"""

from __future__ import annotations

import random
from collections import defaultdict
from typing import Optional, Sequence

from ozone_tpu.scm.node_manager import NodeInfo, NodeManager


class PlacementError(Exception):
    pass


class PlacementPolicy:
    def __init__(self, nodes: NodeManager, seed: Optional[int] = None):
        self.nodes = nodes
        self.rng = random.Random(seed)

    def choose(
        self, count: int, excluded: Sequence[str] = ()
    ) -> list[NodeInfo]:
        raise NotImplementedError

    def _candidates(self, excluded: Sequence[str]) -> list[NodeInfo]:
        ex = set(excluded)
        return [n for n in self.nodes.healthy_in_service()
                if n.dn_id not in ex and n.healthy_volumes != 0]


class RandomPlacement(PlacementPolicy):
    def choose(self, count, excluded=()):
        cands = self._candidates(excluded)
        if len(cands) < count:
            raise PlacementError(
                f"need {count} nodes, only {len(cands)} available"
            )
        return self.rng.sample(cands, count)


class CapacityPlacement(PlacementPolicy):
    """Prefer lower-utilization nodes (SCMContainerPlacementCapacity)."""

    def choose(self, count, excluded=()):
        cands = self._candidates(excluded)
        if len(cands) < count:
            raise PlacementError(
                f"need {count} nodes, only {len(cands)} available"
            )
        def util(n: NodeInfo) -> float:
            return n.used_bytes / n.capacity_bytes if n.capacity_bytes else 0.0
        # weighted-random among the least-utilized half to avoid herding
        cands.sort(key=util)
        pool = cands[: max(count, len(cands) // 2 + 1)]
        return self.rng.sample(pool, count)


class RackScatterPlacement(PlacementPolicy):
    """EC placement: scatter across racks, round-robin by rack
    (SCMContainerPlacementRackScatter)."""

    def choose(self, count, excluded=()):
        cands = self._candidates(excluded)
        if len(cands) < count:
            raise PlacementError(
                f"need {count} nodes, only {len(cands)} available"
            )
        by_rack: dict[str, list[NodeInfo]] = defaultdict(list)
        for n in cands:
            by_rack[n.rack].append(n)
        for nodes in by_rack.values():
            self.rng.shuffle(nodes)
        racks = sorted(by_rack, key=lambda r: -len(by_rack[r]))
        self.rng.shuffle(racks)
        chosen: list[NodeInfo] = []
        while len(chosen) < count:
            progressed = False
            for r in racks:
                if by_rack[r] and len(chosen) < count:
                    chosen.append(by_rack[r].pop())
                    progressed = True
            if not progressed:
                break
        if len(chosen) < count:
            raise PlacementError("insufficient nodes across racks")
        return chosen

    @staticmethod
    def validate(racks_used: int, total_racks: int, count: int) -> bool:
        """Mis-replication check: placement is valid when it uses
        min(count, total_racks) distinct racks."""
        return racks_used >= min(count, max(total_racks, 1))
