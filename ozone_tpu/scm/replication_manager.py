"""Replication manager: detect and repair under/over/mis-replication.

Mirrors server-scm container/replication/ReplicationManager.java:109
(periodic processContainer scan :849-1005 feeding under/over-replication
queues) with the EC machinery: per-replica-index redundancy accounting
(ECContainerReplicaCount), reconstruction command emission
(ECUnderReplicationHandler.processAndSendCommands:107 ->
ReconstructECContainersCommand), over-replication trimming
(ECOverReplicationHandler), and plain re-replication for Ratis containers
(RatisUnderReplicationHandler). Commands are queued on datanodes via the
NodeManager and ride heartbeat responses.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

from ozone_tpu.scm.container_manager import ContainerInfo, ContainerManager
from ozone_tpu.scm.node_manager import NodeManager, NodeState
from ozone_tpu.scm.placement import PlacementError, PlacementPolicy
from ozone_tpu.scm.pipeline import ReplicationType
from ozone_tpu.storage.ids import ContainerState
from ozone_tpu.storage.reconstruction import ReconstructionCommand
from ozone_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


@dataclass
class ReplicateCommand:
    """Copy a container replica to a target node (ReplicateContainerCommand)."""

    container_id: int
    source: str
    target: str
    replica_index: int = 0


@dataclass
class DeleteReplicaCommand:
    container_id: int
    replica_index: int = 0


@dataclass
class HealthReport:
    under_replicated: list[int] = field(default_factory=list)
    over_replicated: list[int] = field(default_factory=list)
    mis_replicated: list[int] = field(default_factory=list)
    unrecoverable: list[int] = field(default_factory=list)


class ECReplicaCount:
    """Per-replica-index accounting for one EC container
    (ECContainerReplicaCount analog). Replicas on decommissioning/
    maintenance nodes don't count toward redundancy but are remembered as
    copy sources (the reference's decommission path replicates instead of
    reconstructing, ECUnderReplicationHandler decommission branch)."""

    def __init__(self, container: ContainerInfo, nodes: NodeManager):
        from ozone_tpu.scm.node_manager import NodeOperationalState

        self.container = container
        k = container.replication.ec.all_units
        self.expected = set(range(1, k + 1))
        self.present: dict[int, list[str]] = {}
        self.draining: dict[int, str] = {}  # index -> decommissioning holder
        for dn_id, r in container.replicas.items():
            n = nodes.get(dn_id)
            if n is None or n.state is NodeState.DEAD:
                continue
            if r.state in ("UNHEALTHY", "DELETED", "INVALID"):
                continue
            if n.op_state is not NodeOperationalState.IN_SERVICE:
                self.draining.setdefault(r.replica_index, dn_id)
                continue
            self.present.setdefault(r.replica_index, []).append(dn_id)

    @property
    def missing_indexes(self) -> list[int]:
        return sorted(self.expected - set(self.present))

    @property
    def excess_indexes(self) -> dict[int, list[str]]:
        return {
            i: dns[1:] for i, dns in self.present.items() if len(dns) > 1
        }

    @property
    def recoverable(self) -> bool:
        k = self.container.replication.ec.data_units
        return len(set(self.present) | set(self.draining)) >= k


class ReplicationManager:
    def __init__(
        self,
        containers: ContainerManager,
        nodes: NodeManager,
        placement: PlacementPolicy,
    ):
        self.containers = containers
        self.nodes = nodes
        self.placement = placement
        self.metrics = MetricsRegistry("scm.replication")
        # in-flight op dedup (ContainerReplicaPendingOps analog)
        self._pending: set[tuple[int, int]] = set()  # (container, index)

    # ------------------------------------------------------------------ scan
    def run_once(self) -> HealthReport:
        report = HealthReport()
        for c in self.containers.containers():
            if c.state in (ContainerState.DELETED, ContainerState.OPEN):
                continue  # open containers are the write path's business
            try:
                self._process_container(c, report)
            except Exception:
                log.exception("processing container %s failed", c.id)
        self.metrics.gauge("under_replicated").set(len(report.under_replicated))
        self.metrics.gauge("over_replicated").set(len(report.over_replicated))
        self.metrics.gauge("unrecoverable").set(len(report.unrecoverable))
        return report

    def _process_container(self, c: ContainerInfo, report: HealthReport) -> None:
        if c.replication.type is ReplicationType.EC:
            self._process_ec(c, report)
        else:
            self._process_ratis(c, report)

    # ------------------------------------------------------------------ EC
    def _process_ec(self, c: ContainerInfo, report: HealthReport) -> None:
        count = ECReplicaCount(c, self.nodes)
        missing = [
            i for i in count.missing_indexes if (c.id, i) not in self._pending
        ]
        if count.missing_indexes and not count.recoverable:
            report.unrecoverable.append(c.id)
            self.metrics.counter("unrecoverable_seen").inc()
            return
        if missing:
            report.under_replicated.append(c.id)
            # indexes still held by draining nodes: plain copy, not decode
            copyable = [i for i in missing if i in count.draining]
            rebuild = [i for i in missing if i not in count.draining]
            for i in copyable:
                src = count.draining[i]
                exclude = [
                    dn for dns in count.present.values() for dn in dns
                ] + [src]
                try:
                    target = self.placement.choose(1, exclude)[0]
                except PlacementError as e:
                    log.warning("no copy target for %s idx %s: %s", c.id, i, e)
                    continue
                self.nodes.queue_command(
                    target.dn_id,
                    ReplicateCommand(c.id, source=src, target=target.dn_id,
                                     replica_index=i),
                )
                self._pending.add((c.id, i))
            if rebuild:
                self._emit_reconstruction(c, count, rebuild)
        for idx, extra_dns in count.excess_indexes.items():
            report.over_replicated.append(c.id)
            for dn in extra_dns:
                self.nodes.queue_command(
                    dn, DeleteReplicaCommand(c.id, replica_index=idx)
                )

    def _emit_reconstruction(
        self, c: ContainerInfo, count: ECReplicaCount, missing: list[int]
    ) -> None:
        sources = {i: dns[0] for i, dns in count.present.items()}
        exclude = [dn for dns in count.present.values() for dn in dns]
        try:
            chosen = self.placement.choose(len(missing), exclude)
        except PlacementError as e:
            log.warning("no targets for reconstruction of %s: %s", c.id, e)
            return
        targets = {i: n.dn_id for i, n in zip(missing, chosen)}
        cmd = ReconstructionCommand(
            container_id=c.id,
            replication=c.replication.ec,
            sources=sources,
            targets=targets,
        )
        # the first target node coordinates (reference sends the command to
        # one DN which executes reconstruction for all targets)
        coordinator = chosen[0].dn_id
        self.nodes.queue_command(coordinator, cmd)
        for i in missing:
            self._pending.add((c.id, i))
        self.metrics.counter("reconstructions_emitted").inc()

    # ------------------------------------------------------------------ Ratis
    def _process_ratis(self, c: ContainerInfo, report: HealthReport) -> None:
        live = [
            dn
            for dn, r in c.replicas.items()
            if (n := self.nodes.get(dn)) is not None
            and n.state is not NodeState.DEAD
            and r.state not in ("UNHEALTHY", "DELETED")
        ]
        want = c.replication.factor
        if len(live) < want:
            if not live:
                report.unrecoverable.append(c.id)
                return
            report.under_replicated.append(c.id)
            if (c.id, 0) in self._pending:
                return
            try:
                chosen = self.placement.choose(want - len(live), live)
            except PlacementError as e:
                log.warning("no replication targets for %s: %s", c.id, e)
                return
            for n in chosen:
                self.nodes.queue_command(
                    n.dn_id,
                    ReplicateCommand(c.id, source=live[0], target=n.dn_id),
                )
            self._pending.add((c.id, 0))
        elif len(live) > want:
            report.over_replicated.append(c.id)
            for dn in live[want:]:
                self.nodes.queue_command(dn, DeleteReplicaCommand(c.id))

    # ------------------------------------------------------------------ acks
    def op_completed(self, container_id: int, replica_index: int = 0) -> None:
        self._pending.discard((container_id, replica_index))

    def clear_pending(self) -> None:
        self._pending.clear()
