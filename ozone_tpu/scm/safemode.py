"""SCM safemode: block allocation gated on cluster readiness.

Mirrors server-scm safemode/SCMSafeModeManager.java:84 + exit rules:
DataNodeSafeModeRule (min registered DN count), ContainerSafeModeRule
(fraction of containers with at least one reported replica), and a
healthy-pipeline rule.
"""

from __future__ import annotations

from dataclasses import dataclass

from ozone_tpu.scm.container_manager import ContainerManager
from ozone_tpu.scm.node_manager import NodeManager
from ozone_tpu.storage.ids import ContainerState


class SafeModeError(Exception):
    pass


@dataclass
class SafeModeConfig:
    min_datanodes: int = 1
    container_replica_fraction: float = 0.99


class SafeModeManager:
    def __init__(
        self,
        nodes: NodeManager,
        containers: ContainerManager,
        config: SafeModeConfig = SafeModeConfig(),
    ):
        self.nodes = nodes
        self.containers = containers
        self.config = config
        self._forced: bool | None = None  # admin override

    def force(self, in_safemode: bool | None) -> None:
        """Admin override ('ozone admin safemode enter/exit' analog)."""
        self._forced = in_safemode

    def status(self) -> dict:
        relevant = [
            c
            for c in self.containers.containers()
            if c.state in (ContainerState.CLOSED, ContainerState.QUASI_CLOSED)
        ]
        with_replica = sum(1 for c in relevant if c.replicas)
        return {
            "datanodes": self.nodes.node_count(),
            "datanodes_required": self.config.min_datanodes,
            "containers_with_replica": with_replica,
            "containers_total": len(relevant),
        }

    def in_safemode(self) -> bool:
        if self._forced is not None:
            return self._forced
        s = self.status()
        if s["datanodes"] < s["datanodes_required"]:
            return True
        if s["containers_total"]:
            frac = s["containers_with_replica"] / s["containers_total"]
            if frac < self.config.container_replica_fraction:
                return True
        return False

    def check_allocation_allowed(self) -> None:
        """Raises while in safemode (BlockManagerImpl safemode precheck
        :154)."""
        if self.in_safemode():
            raise SafeModeError(f"SCM is in safemode: {self.status()}")
