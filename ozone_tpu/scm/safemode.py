"""SCM safemode: block allocation gated on cluster readiness.

Mirrors server-scm safemode/SCMSafeModeManager.java:84 + exit rules:
DataNodeSafeModeRule (min registered DN count), ContainerSafeModeRule
(fraction of containers with at least one reported replica),
HealthyPipelineSafeModeRule (fraction of recovered OPEN pipelines with
every member re-registered HEALTHY), and OneReplicaPipelineSafeModeRule
(fraction of recovered pipelines with at least one member back).
"""

from __future__ import annotations

from dataclasses import dataclass

from ozone_tpu.scm.container_manager import ContainerManager
from ozone_tpu.scm.node_manager import NodeManager, NodeState
from ozone_tpu.scm.pipeline import PipelineState
from ozone_tpu.storage.ids import ContainerState


class SafeModeError(Exception):
    pass


@dataclass
class SafeModeConfig:
    min_datanodes: int = 1
    container_replica_fraction: float = 0.99
    # reference defaults: hdds.scm.safemode.healthy.pipeline.pct 0.10,
    # hdds.scm.safemode.atleast.one.node.reported.pipeline.pct 0.90
    healthy_pipeline_fraction: float = 0.10
    one_replica_pipeline_fraction: float = 0.90


class SafeModeManager:
    def __init__(
        self,
        nodes: NodeManager,
        containers: ContainerManager,
        config: SafeModeConfig = SafeModeConfig(),
    ):
        self.nodes = nodes
        self.containers = containers
        self.config = config
        self._forced: bool | None = None  # admin override
        # safemode exit is ONE-WAY (reference SCMSafeModeManager): once
        # the rules pass, later node flaps must not re-gate allocation
        self._exited = False
        # the pipeline rules gate on pipelines RECOVERED from the store
        # at startup (the reference's pre-existing pipeline set) — new
        # pipelines created after startup never hold up safemode exit,
        # and pipelines closed/removed since drop out of the rule set
        # only pipelines still carrying writes matter: recovery marks
        # retired pipelines CLOSED, so the live set is simply the OPEN
        # ones at startup
        self._initial_pipeline_ids = {
            p.id
            for p in containers.pipelines()
            if p.state is PipelineState.OPEN
        }

    def force(self, in_safemode: bool | None) -> None:
        """Admin override ('ozone admin safemode enter/exit' analog)."""
        self._forced = in_safemode

    def _pipeline_counts(self) -> tuple[int, int, int]:
        """(total, fully-healthy, with-at-least-one-member) over the
        startup-recovered pipelines that still exist (a scrubbed/closed
        pipeline must not hold safemode forever)."""
        total = healthy = one = 0
        for p in self.containers.pipelines():
            if (p.id not in self._initial_pipeline_ids
                    or p.state is not PipelineState.OPEN):
                # a pipeline closed since startup (dead member, scrub)
                # stops gating: its data's safety is the container and
                # replication-manager rules' concern
                continue
            total += 1
            states = []
            for dn_id in p.nodes:
                n = self.nodes.get(dn_id)
                states.append(n.state if n is not None else None)
            if states and all(st is NodeState.HEALTHY for st in states):
                healthy += 1
            if any(st is not None for st in states):
                one += 1
        return total, healthy, one

    def status(self) -> dict:
        relevant = [
            c
            for c in self.containers.containers()
            if c.state in (ContainerState.CLOSED, ContainerState.QUASI_CLOSED)
        ]
        with_replica = sum(1 for c in relevant if c.replicas)
        total_p, healthy_p, one_p = self._pipeline_counts()
        return {
            "datanodes": self.nodes.node_count(),
            "datanodes_required": self.config.min_datanodes,
            "containers_with_replica": with_replica,
            "containers_total": len(relevant),
            "pipelines_total": total_p,
            "pipelines_healthy": healthy_p,
            "pipelines_with_member": one_p,
        }

    def in_safemode(self) -> bool:
        if self._forced is not None:
            return self._forced
        if self._exited:
            return False
        s = self.status()
        if s["datanodes"] < s["datanodes_required"]:
            return True
        if s["containers_total"]:
            frac = s["containers_with_replica"] / s["containers_total"]
            if frac < self.config.container_replica_fraction:
                return True
        if s["pipelines_total"]:
            if (s["pipelines_healthy"] / s["pipelines_total"]
                    < self.config.healthy_pipeline_fraction):
                return True
            if (s["pipelines_with_member"] / s["pipelines_total"]
                    < self.config.one_replica_pipeline_fraction):
                return True
        self._exited = True  # rules passed: exit is permanent
        return False

    def check_allocation_allowed(self) -> None:
        """Raises while in safemode (BlockManagerImpl safemode precheck
        :154)."""
        if self.in_safemode():
            raise SafeModeError(f"SCM is in safemode: {self.status()}")
