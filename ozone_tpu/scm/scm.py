"""StorageContainerManager facade: wires node/pipeline/container/block
management, safemode, and the replication control loop.

Mirror of server-scm StorageContainerManager.java:228
(initializeSystemManagers:648 wiring) at framework scale: one object the
OM, datanodes, and admin tools talk to. Heartbeat handling mirrors
SCMNodeManager.processHeartbeat (commands ride the response); dead-node
events trigger replica cleanup + replication scans (DeadNodeHandler).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.scm import node_manager as nm
from ozone_tpu.scm.container_manager import ContainerManager
from ozone_tpu.scm.node_manager import NodeManager, NodeOperationalState
from ozone_tpu.scm.placement import RackScatterPlacement
from ozone_tpu.scm.replication_manager import ReplicationManager
from ozone_tpu.scm.safemode import SafeModeConfig, SafeModeManager
from ozone_tpu.scm.pipeline import ReplicationConfig
from ozone_tpu.utils.events import EventQueue
from ozone_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


class StorageContainerManager:
    def __init__(
        self,
        min_datanodes: int = 1,
        container_size: int = 5 * 1024 * 1024 * 1024,
        placement_seed: Optional[int] = None,
        stale_after_s: float = 9.0,
        dead_after_s: float = 30.0,
        db_path=None,
        block_tokens: bool = False,
    ):
        self.events = EventQueue()
        # symmetric secret keys for block/container tokens (reference
        # security/symmetric/SecretKeyManager lives in the SCM and feeds
        # OM + datanodes). Keys are minted lazily by ensure_secret_key so
        # HA replicas can replicate the material through the ring instead
        # of each inventing their own.
        from ozone_tpu.utils.security import SecretKeyManager

        self.block_tokens = block_tokens
        self.secret_keys = SecretKeyManager(generate=False,
                                            activation_s=10.0)
        #: HA hook: leader routes freshly minted keys through the ring
        #: (apply lands in apply_admin_op("import-secret-key")); None =
        #: single-node, install directly
        self.on_secret_rotate = None
        self.nodes = NodeManager(
            self.events, stale_after_s=stale_after_s, dead_after_s=dead_after_s
        )
        self.placement = RackScatterPlacement(self.nodes, seed=placement_seed)
        self.containers = ContainerManager(
            self.nodes, self.placement, container_size=container_size,
            db_path=db_path,
        )
        # durable op-state round trip: the SCM store is authoritative
        # across restarts; DN echoes cover a store-less SCM
        self.nodes.seed_op_states(self.containers.node_op_states())
        self.nodes.on_op_state_change = \
            self.containers.persist_node_op_state
        self.safemode = SafeModeManager(
            self.nodes, self.containers, SafeModeConfig(min_datanodes)
        )
        # layout-version manager for the metadata services themselves
        # (HDDSLayoutFeature analog); persisted next to the SCM store
        # when one exists, in-memory (fresh = finalized) otherwise
        self.layout = None
        self.finalizer = None
        if db_path is not None:
            from pathlib import Path

            from ozone_tpu.utils.upgrade import (
                LayoutVersionManager,
                UpgradeFinalizer,
            )

            self.layout = LayoutVersionManager(
                Path(db_path).parent / "layout_version.json"
            )
            # ONE persistent finalizer so future features can register
            # migration actions on it (BasicUpgradeFinalizer contract)
            self.finalizer = UpgradeFinalizer(self.layout)
        self.replication = ReplicationManager(
            self.containers, self.nodes, self.placement
        )
        from ozone_tpu.scm.balancer import ContainerBalancer
        from ozone_tpu.scm.block_deletion import (
            BlockDeletingService,
            DeletedBlockLog,
        )
        from ozone_tpu.scm.decommission import DecommissionMonitor

        self.balancer = ContainerBalancer(self.containers, self.nodes)
        # resume a persisted balancing run (the reference's
        # StatefulServiceStateManager read at ContainerBalancer start,
        # ContainerBalancer.java:391): config + progress counters come
        # back from the replicated store; the running flag itself is
        # always read live from it (see balancer_enabled)
        self._hydrate_balancer_from_state()
        self.decommission_monitor = DecommissionMonitor(
            self.nodes, self.containers, self.replication
        )
        self.deleted_blocks = DeletedBlockLog()
        self.block_deleting = BlockDeletingService(
            self.deleted_blocks, self.nodes
        )
        self.metrics = MetricsRegistry("scm")
        self.events.subscribe(nm.DEAD_NODE, self._on_dead_node)
        self._bg: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- datanodes
    def register_datanode(
        self, dn_id: str, rack: str = "/default-rack",
        capacity_bytes: int = 0, op_state=None,
    ) -> None:
        self.nodes.register(dn_id, rack, capacity_bytes, op_state=op_state)
        self.metrics.counter("registrations").inc()

    def heartbeat(
        self,
        dn_id: str,
        container_report: Optional[list[dict]] = None,
        used_bytes: int = 0,
        deleted_block_acks: Optional[list[int]] = None,
        layout_version: Optional[int] = None,
        healthy_volumes: Optional[int] = None,
    ) -> list:
        """Process a heartbeat (+optional full container report and block-
        deletion acks); return the commands queued for this datanode."""
        if deleted_block_acks:
            self.deleted_blocks.ack(dn_id, deleted_block_acks)
        if container_report is not None:
            self.containers.process_container_report(dn_id, container_report)
            # CLOSING -> CLOSED once replicas report closed
            for r in container_report:
                c = self.containers.get_or_none(int(r["container_id"]))
                if (
                    c is not None
                    and r["state"] in ("CLOSED", "QUASI_CLOSED")
                    and c.state.value in ("OPEN", "CLOSING")
                ):
                    self.containers.mark_closed(c.id)
        self.metrics.counter("heartbeats").inc()
        if layout_version is not None or healthy_volumes is not None:
            n = self.nodes.get(dn_id)
            if n is not None:
                if layout_version is not None:
                    n.layout_version = int(layout_version)
                if healthy_volumes is not None:
                    n.healthy_volumes = int(healthy_volumes)
        return self.nodes.process_heartbeat(dn_id, used_bytes)

    def _on_dead_node(self, dn_id: str) -> None:
        # events are published outside the NodeManager lock (deadlock
        # avoidance), so the node may have heartbeated back between the
        # transition and this dispatch — re-validate before purging a
        # healthy node's replica records
        n = self.nodes.get(dn_id)
        if n is None or n.state is not nm.NodeState.DEAD:
            log.info("node %s recovered before dead-node handling; skipped",
                     dn_id)
            return
        affected = self.containers.remove_replicas_of_node(dn_id)
        log.info("node %s dead; %d containers affected", dn_id, len(affected))
        self.metrics.counter("dead_nodes").inc()

    # ------------------------------------------------------------- allocation
    def allocate_block(
        self,
        replication: ReplicationConfig,
        block_size: int,
        excluded: Optional[list[str]] = None,
        excluded_containers: Optional[list[int]] = None,
    ) -> BlockGroup:
        self.safemode.check_allocation_allowed()
        g = self.containers.allocate_block(replication, block_size, excluded,
                                           excluded_containers)
        self.metrics.counter("blocks_allocated").inc()
        return g

    def delete_blocks(self, entries: list[tuple]) -> list[int]:
        """OM -> SCM deletion handoff (ScmBlockLocationProtocol
        .deleteKeyBlocks analog): entries of (BlockID, datanode ids)."""
        tx_ids = [
            self.deleted_blocks.add(bid, nodes) for bid, nodes in entries
        ]
        self.metrics.counter("block_delete_txs").inc(len(tx_ids))
        return tx_ids

    # ------------------------------------------------------------- admin ops
    def decommission(self, dn_id: str) -> None:
        """Start draining a node (NodeDecommissionManager.java:60): out of
        placement; the replication manager re-protects its containers and
        the monitor finalizes once drained."""
        self.decommission_monitor.start_decommission(dn_id)

    def apply_admin_op(self, op: str, target=None) -> dict:
        """Deterministic admin mutation + state read-back. One function
        serves both the direct (single-node) path and the HA ring's
        replicated apply, so every replica ends in the same state
        (`ozone admin` node/balancer/safemode verbs)."""
        from ozone_tpu.storage.ids import ContainerState, StorageError

        if op in ("decommission", "recommission", "maintenance"):
            node = self.nodes.get(target) if target else None
            if node is None:
                raise StorageError("NODE_NOT_FOUND",
                                   f"unknown datanode {target!r}")
            if op == "decommission":
                self.decommission(target)
            elif op == "recommission":
                self.decommission_monitor.recommission(target)
            else:
                self.decommission_monitor.start_maintenance(target)
            return {"node": target, "op_state": node.op_state.value}
        if op == "finalize-upgrade":
            state = None
            if self.finalizer is not None:
                state = self.finalizer.finalize().value
            for n in self.nodes.nodes():
                self.nodes.queue_command(n.dn_id, {"type": "finalize"})
            return {"scm": state,
                    "datanodes_notified": self.nodes.node_count()}
        def _numeric_id(kind: str) -> int:
            try:
                return int(target)
            except (TypeError, ValueError):
                raise StorageError("INVALID",
                                   f"{kind} id must be numeric: "
                                   f"{target!r}")

        if op == "close-container":
            cid = _numeric_id("container")
            c = self.containers.get_or_none(cid)
            if c is None:
                raise StorageError("CONTAINER_NOT_FOUND",
                                   f"unknown container {target!r}")

            if c.state is ContainerState.OPEN:
                # the normal close flow: CLOSING + close commands to the
                # replicas; convergence marks it CLOSED
                self.containers.finalize_container(c.id)
            return {"container": c.id, "state": c.state.value}
        if op == "close-pipeline":
            # ozone admin pipeline close <id>: pipelines are 1:1 with
            # their container here, so closing the pipeline finalizes
            # the container (writes stop, members drop the raft group)
            pid = _numeric_id("pipeline")
            for c in self.containers.containers():
                if c.pipeline is not None and c.pipeline.id == pid:
                    if c.state is ContainerState.OPEN:
                        self.containers.finalize_container(c.id)
                    return {"pipeline": pid, "container": c.id,
                            "state": c.state.value}
            raise StorageError("PIPELINE_NOT_FOUND",
                               f"unknown pipeline {target!r}")
        if op == "import-secret-key":
            # token secret-key rotation decision (possibly replicated
            # through the HA ring): install the material on this replica
            from ozone_tpu.utils.security import SecretKey

            self.secret_keys.import_key(SecretKey.from_json(target))
            return {"key_id": target["key_id"]}
        if op == "balancer-start":
            if isinstance(target, dict):
                # operator config overrides ride the replicated admin
                # decision, so every replica balances identically
                self._apply_balancer_config(target)
            self.balancer_enabled = True
        elif op == "balancer-stop":
            self.balancer_enabled = False
        elif op == "safemode-enter":
            self.safemode.force(True)
        elif op == "safemode-exit":
            self.safemode.force(False)
        else:
            raise StorageError("UNSUPPORTED_REQUEST", f"admin op {op!r}")
        if op.startswith("balancer"):
            return self.balancer_status()
        return {"safemode": self.safemode.in_safemode(),
                **self.safemode.status()}

    # ------------------------------------------------------------- balancer
    def _apply_balancer_config(self, src: dict) -> None:
        """Copy config knobs present in `src` onto the live config — the
        ONE field list (dataclasses.fields) shared by operator override,
        row hydration, and persistence, so a new knob cannot silently
        drop out of one of them."""
        import dataclasses

        cfg = self.balancer.config
        for f in dataclasses.fields(cfg):
            if f.name in src:
                cur = getattr(cfg, f.name)
                setattr(cfg, f.name, type(cur)(src[f.name]))

    def _hydrate_balancer_from_state(self) -> None:
        """Pull the replicated service row into the live balancer. The
        row is authoritative for CONFIG (a promoted follower's in-memory
        balancer still holds defaults — using them would clobber the
        operator's replicated settings); progress counters take the max
        of memory and row so an idle leader's unpersisted iteration
        count is never rolled back."""
        svc = self.containers.service_state("balancer")
        if not svc:
            return
        self._apply_balancer_config(svc)
        st = self.balancer.status
        st.iterations = max(st.iterations, int(svc.get("iterations", 0)))
        st.moves_scheduled = max(
            st.moves_scheduled, int(svc.get("moves_scheduled", 0)))
        st.bytes_scheduled = max(
            st.bytes_scheduled, int(svc.get("bytes_scheduled", 0)))

    @property
    def balancer_enabled(self) -> bool:
        """Live view of the persisted running flag: replicas learn it
        through the replicated service-state row, so a promoted follower
        resumes balancing without any re-start command."""
        svc = self.containers.service_state("balancer")
        return bool(svc and svc.get("running"))

    @balancer_enabled.setter
    def balancer_enabled(self, running: bool) -> None:
        self._persist_balancer_state(running=bool(running))

    def _persist_balancer_state(self, running=None) -> None:
        """Write the balancer's StatefulService record (config + progress,
        ContainerBalancer.java:281 saveConfiguration) through the store so
        restart and failover resume mid-run."""
        import dataclasses

        svc = self.containers.service_state("balancer") or {}
        if running is None:
            running = bool(svc.get("running"))
        st = self.balancer.status
        self.containers.persist_service_state("balancer", {
            "running": bool(running),
            **dataclasses.asdict(self.balancer.config),
            "iterations": st.iterations,
            "moves_scheduled": st.moves_scheduled,
            "bytes_scheduled": st.bytes_scheduled,
        })

    def balancer_status(self) -> dict:
        """Live progress: in-memory counters run ahead of the persisted
        row on move-less iterations (which are not persisted), so report
        whichever is larger — status must not look frozen while
        running."""
        svc = self.containers.service_state("balancer") or {}
        st = self.balancer.status
        return {
            "running": self.balancer_enabled,
            "iterations": max(st.iterations,
                              int(svc.get("iterations", 0))),
            "moves_scheduled": max(st.moves_scheduled,
                                   int(svc.get("moves_scheduled", 0))),
            "bytes_scheduled": max(st.bytes_scheduled,
                                   int(svc.get("bytes_scheduled", 0))),
            "threshold": float(
                svc.get("threshold", self.balancer.config.threshold)),
        }

    # ------------------------------------------------------------- security
    def ensure_secret_key(self) -> None:
        """Mint/rotate the token-signing key when due. Single-node
        installs directly; under HA the daemon's on_secret_rotate hook
        replicates the material through the metadata ring so every
        replica (and thus every OM issuer) signs with the same keys."""
        if not self.block_tokens or not self.secret_keys.needs_rotation():
            return
        key = self.secret_keys.new_key()
        if self.on_secret_rotate is not None:
            self.on_secret_rotate(key)
        else:
            self.secret_keys.import_key(key)

    # ------------------------------------------------------------- background
    def run_background_once(self) -> None:
        """One tick of the SCM control loops (liveness + replication +
        decommission + balancer)."""
        self.ensure_secret_key()
        self.nodes.check_liveness()
        if not self.safemode.in_safemode():
            self.replication.run_once()
            self.decommission_monitor.run_once()
            self.block_deleting.run_once()
            self.containers.resend_closing()
            if self.balancer_enabled:
                # replicated row first: a freshly promoted follower must
                # balance with the operator's config, not defaults
                self._hydrate_balancer_from_state()
                moves = self.balancer.run_iteration()
                if moves:
                    # persist progress only when something was scheduled —
                    # an idle tick must not append a WAL/replication
                    # record every second
                    self._persist_balancer_state()

    def start_background(self, interval_s: float = 1.0) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.run_background_once()
                except Exception:
                    log.exception("scm background tick failed")

        self._bg = threading.Thread(target=loop, name="scm-bg", daemon=True)
        self._bg.start()

    def stop(self) -> None:
        self._stop.set()
        if self._bg:
            self._bg.join(timeout=5)
