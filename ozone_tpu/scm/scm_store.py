"""SCM metadata persistence.

Role analog of the reference's SCM RocksDB metadata store (server-scm
persists containers/pipelines/sequence ids; replicas are soft state
rebuilt from datanode full container reports). Sqlite-backed: container
rows + monotonic id counters (SequenceIdGenerator analog — persisted
before use so restarts never reissue an id).
"""

from __future__ import annotations

import json
import sqlite3
import threading
from pathlib import Path


class ScmStore:
    def __init__(self, path):
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        self._conn = sqlite3.connect(str(p), check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS containers "
            "(id INTEGER PRIMARY KEY, data TEXT)"
        )
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS meta (k TEXT PRIMARY KEY, v TEXT)"
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.commit()
        self._lock = threading.Lock()

    def save_container(self, row: dict, counters: tuple[int, int]) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO containers VALUES (?, ?)",
                (row["id"], json.dumps(row)),
            )
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('counters', ?)",
                (json.dumps(list(counters)),),
            )
            self._conn.commit()

    def load(self) -> dict:
        with self._lock:
            rows = self._conn.execute(
                "SELECT data FROM containers ORDER BY id"
            ).fetchall()
            meta = self._conn.execute(
                "SELECT v FROM meta WHERE k='counters'"
            ).fetchone()
            ops = self._conn.execute(
                "SELECT v FROM meta WHERE k='node_op_states'"
            ).fetchone()
            pidf = self._conn.execute(
                "SELECT v FROM meta WHERE k='pipeline_floor'"
            ).fetchone()
        counters = json.loads(meta[0]) if meta else [1, 1]
        with self._lock:
            svc = self._conn.execute(
                "SELECT v FROM meta WHERE k='service_states'"
            ).fetchone()
        return {
            "containers": [json.loads(r[0]) for r in rows],
            "next_container_id": counters[0],
            "next_local_id": counters[1],
            "pipeline_floor": json.loads(pidf[0]) if pidf else 1,
            "node_op_states": json.loads(ops[0]) if ops else {},
            "service_states": json.loads(svc[0]) if svc else {},
        }

    def replace_service_states(self, states: dict) -> None:
        """Replace-all write of the service-state map (snapshot install)."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('service_states', ?)",
                (json.dumps(states),),
            )
            self._conn.commit()

    def save_service_state(self, name: str, state: dict) -> None:
        """Durably record a background service's config + progress (the
        reference's StatefulServiceStateManager rows,
        StatefulServiceStateManagerImpl.java:71): a restarted or failed-
        over SCM resumes the service where it stopped."""
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM meta WHERE k='service_states'"
            ).fetchone()
            states = json.loads(row[0]) if row else {}
            states[name] = state
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('service_states', ?)",
                (json.dumps(states),),
            )
            self._conn.commit()

    def save_counters(self, counters: tuple[int, int],
                      pipeline_floor: int | None = None) -> None:
        """Durably raise the id floors WITHOUT a container row — the
        commit-first range reservations (SequenceIdGenerator analog,
        server-scm ha/SequenceIdGenerator.java:52-84) persist their
        raised floor the moment the record applies, so a restart can
        never re-issue an id from a range already handed to a leader."""
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('counters', ?)",
                (json.dumps(list(counters)),),
            )
            if pipeline_floor is not None:
                self._conn.execute(
                    "INSERT OR REPLACE INTO meta VALUES "
                    "('pipeline_floor', ?)",
                    (json.dumps(int(pipeline_floor)),),
                )
            self._conn.commit()

    def save_node_op_state(self, dn_id: str, state: str) -> None:
        """Durably record a node's operational state (IN_SERVICE clears
        the entry) — a restarted SCM must not forget an in-flight drain."""
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM meta WHERE k='node_op_states'"
            ).fetchone()
            states = json.loads(row[0]) if row else {}
            if state == "IN_SERVICE":
                states.pop(dn_id, None)
            else:
                states[dn_id] = state
            self._conn.execute(
                "INSERT OR REPLACE INTO meta VALUES ('node_op_states', ?)",
                (json.dumps(states),),
            )
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()
