"""Commit-first, term-fenced sequence-id issuance for SCM HA.

Role analog of the reference's SequenceIdGenerator (server-scm
ha/SequenceIdGenerator.java:52-84, consumed by
block/BlockManagerImpl.java:188): ids are handed to callers ONLY from
ranges that were already committed through the consensus ring. The
leader reserves a batch via a replicated record, waits for the quorum
commit, and then issues from the batch locally; a leadership change
invalidates the local batch. Because every replica's committed floor is
raised past each reserved range BEFORE any id in it is exposed, two
leaders (or two terms of the same leader) can never issue the same id —
duplicate (container, local_id) pairs are impossible by construction,
which is the property whose absence corrupted acked data across
leadership hand-offs (KNOWN_ISSUES.md round 3).

Gaps are deliberate and harmless: an invalidated batch's unissued tail
is burned, exactly like the reference's invalidateBatch on leader
change.
"""

from __future__ import annotations

import threading
from typing import Callable

#: ids reserved per ring round-trip; block ids dominate allocation
#: traffic so they get the big batch (reference default batch 1000)
DEFAULT_BATCH_SIZES = {"block": 1000, "container": 16, "pipeline": 16}


class SequenceIdGenerator:
    """Issue ids from quorum-committed ranges only.

    ``reserve_fn(kind, count) -> (lo, hi)`` must return a half-open
    range that IS ALREADY COMMITTED through the ring when it returns
    (propose + await apply); it raises when this node is not the leader.
    ``invalidate()`` must be called on any leadership change.
    """

    def __init__(
        self,
        reserve_fn: Callable[[str, int], tuple[int, int]],
        batch_sizes: dict[str, int] | None = None,
    ):
        self._reserve_fn = reserve_fn
        self._batch_sizes = dict(batch_sizes or DEFAULT_BATCH_SIZES)
        self._lock = threading.Lock()  # guards batches/free/epoch
        self._batches: dict[str, list[int]] = {}  # kind -> [cursor, hi)
        self._free: dict[str, list[int]] = {}  # released, never-exposed ids
        self._epoch = 0
        # one reservation in flight per kind; other callers of the same
        # kind wait on it instead of burning parallel ranges
        self._reserve_locks: dict[str, threading.Lock] = {}

    def next(self, kind: str) -> int:
        """One globally-unique id. May block on a ring round-trip when
        the local batch is exhausted; raises the reserve_fn's error
        (NotRaftLeaderError) when this node cannot reserve."""
        while True:
            with self._lock:
                epoch = self._epoch
                free = self._free.get(kind)
                if free:
                    return free.pop()
                b = self._batches.get(kind)
                if b is not None and b[0] < b[1]:
                    b[0] += 1
                    return b[0] - 1
                rlock = self._reserve_locks.setdefault(
                    kind, threading.Lock())
            with rlock:
                with self._lock:
                    b = self._batches.get(kind)
                    if (b is not None and b[0] < b[1]) \
                            or self._free.get(kind):
                        continue  # another thread refilled while we waited
                count = self._batch_sizes.get(kind, 64)
                # ring round-trip OUTSIDE every other lock: the apply
                # path (raft-node lock -> container lock) must stay free
                lo, hi = self._reserve_fn(kind, count)
                with self._lock:
                    if self._epoch == epoch:
                        self._batches[kind] = [lo, hi]
                    # epoch moved mid-reservation (step-down raced the
                    # commit): burn the committed range — issuing from it
                    # here would be safe for uniqueness (no other node
                    # can ever reserve below the raised floor) but this
                    # node may no longer be entitled to serve

    @property
    def epoch(self) -> int:
        """Invalidation epoch; capture BEFORE next() to hand release()
        a token proving the id predates no step-down."""
        with self._lock:
            return self._epoch

    def release(self, kind: str, id_: int, epoch: int | None = None) -> None:
        """Return a never-exposed id for reuse. Only ids obtained from
        next() may be released, and at most once — they re-enter the
        local free list, which is still unique-by-construction because
        no other node can ever reserve below this range's committed
        ceiling. `epoch` (captured via .epoch before the matching
        next()) keeps the documented burn contract exact: if the
        generator was invalidated since, the id belongs to a burned
        batch and is dropped instead of re-entering the fresh free
        list (a deposed-then-re-elected leader must not issue from a
        batch its step-down burned)."""
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                return
            self._free.setdefault(kind, []).append(id_)

    def invalidate(self) -> None:
        """Leadership changed: burn local batches and free lists (the
        reference's invalidateBatch on notifyLeaderChanged). Safe to
        call from raft callbacks — only takes the generator's own
        lock."""
        with self._lock:
            self._epoch += 1
            self._batches.clear()
            self._free.clear()
