"""Multi-level network topology: location paths, distance, nearest-first.

Mirror of the reference's NetworkTopologyImpl (hadoop-hdds/common
hdds/scm/net/NetworkTopologyImpl.java:51): cluster locations form a tree
("/dc1/rack2" — any depth), a node's full path is its location plus the
node itself, and distance between two nodes is the number of tree edges
on the path between them (NetworkTopologyImpl.getDistanceCost). The
reference uses this for topology-aware placement and for sorting replica
reads nearest-first (XceiverClientGrpc via sortDatanodes); here the same
ordering feeds client/replicated.py and the EC reader's survivor choice.

Locations are plain strings — the tree is implicit in the path
components, so no registration step is needed beyond knowing each
node's location (shipped on the SCM address book).
"""

from __future__ import annotations

from typing import Iterable, Optional


def norm_location(loc: Optional[str]) -> tuple[str, ...]:
    """Split a location path into components ("/dc/rack" -> (dc, rack));
    empty/None -> the root."""
    if not loc:
        return ()
    return tuple(p for p in loc.split("/") if p)


def distance(loc_a: Optional[str], loc_b: Optional[str],
             node_a: Optional[str] = None,
             node_b: Optional[str] = None) -> int:
    """Tree-edge distance between two nodes at the given locations.

    Same node: 0. Same location: 2 (up to the shared rack, down again).
    Generally: (depth_a - common) + (depth_b - common) + 2 where common
    is the shared path prefix length — the +2 being the two node->rack
    edges (NetworkTopologyImpl.getDistanceCost semantics with nodes as
    leaves)."""
    if node_a is not None and node_a == node_b:
        return 0
    a, b = norm_location(loc_a), norm_location(loc_b)
    common = 0
    for x, y in zip(a, b):
        if x != y:
            break
        common += 1
    return (len(a) - common) + (len(b) - common) + 2


def sort_by_distance(reader_loc: Optional[str],
                     nodes: Iterable[str],
                     locations: dict[str, str],
                     reader_node: Optional[str] = None) -> list[str]:
    """Nodes ordered nearest-first from the reader's position; ties keep
    the input order (stable), unknown locations sort last at their
    original relative order."""
    seq = list(nodes)

    def key(item):
        i, dn = item
        loc = locations.get(dn)
        if loc is None and dn not in locations:
            return (9999, i)
        return (distance(reader_loc, loc, node_a=reader_node, node_b=dn), i)

    return [dn for _, dn in
            sorted(enumerate(seq), key=lambda p: key(p))]
