"""Chunk IO: file-per-block layout with offset writes.

Mirrors the reference datanode's default chunk layout strategy
(container-service keyvalue/impl/FilePerBlockStrategy.java:69 — one file
per block, chunks written at their block offset) and ChunkUtils
(keyvalue/helpers/ChunkUtils.java: writeData:109-156 with overwrite
validation :285, readData:190-283). Durability via explicit flush+fsync on
commit rather than per-write.

Round-4 host-path work: the write path is zero-copy and open-once — a
bounded per-store fd cache (the reference FilePerBlockStrategy's
OpenFiles cache) plus `os.pwrite(fd, memoryview(data), offset)` replaces
open-per-chunk + `tobytes()` (which paid a 1 MiB copy AND an open/close
per chunk); reads use `os.pread` on the same cached fd. Descriptors are
refcounted so the store lock covers only cache bookkeeping — the actual
pwrite/pread/fsync syscalls run outside it and concurrent readers are
never serialized behind a committing writer's fsync. Measured on this
rig: 1 MiB gRPC WriteChunk round-trip 4.22 -> 2.70 CPU ms (237 -> 370
MiB/s/core); the store layer itself 0.49 -> 0.38 ms (docs/PERF.md
per-layer table).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from pathlib import Path

import numpy as np

from ozone_tpu.storage.ids import (
    INVALID_WRITE_SIZE,
    IO_EXCEPTION,
    BlockID,
    ChunkInfo,
    StorageError,
)

#: open block-file descriptors kept per store (= per container). Writers
#: touch one or two blocks of a container at a time, so a small cache
#: captures ~all reuse while bounding total fds across many containers.
_FD_CACHE_CAP = 16


class _CachedFd:
    __slots__ = ("fd", "refs", "evicted")

    def __init__(self, fd: int):
        self.fd = fd
        self.refs = 0
        self.evicted = False


class FilePerBlockStore:
    """Chunks of a block live in one file `<chunks_dir>/<local_id>.block`."""

    def __init__(self, chunks_dir: Path, readonly: bool = False):
        self.chunks_dir = Path(chunks_dir)
        self.readonly = readonly
        if not readonly:
            self.chunks_dir.mkdir(parents=True, exist_ok=True)
        self._fds: OrderedDict[int, _CachedFd] = OrderedDict()
        self._lock = threading.Lock()

    def block_path(self, block_id: BlockID) -> Path:
        return self.chunks_dir / f"{block_id.local_id}.block"

    # ------------------------------------------------------------- fd cache
    def _acquire(self, block_id: BlockID, create: bool) -> _CachedFd:
        """Pin a cached descriptor for a block file (FilePerBlockStrategy
        OpenFiles analog). Release with _release; IO on the pinned fd runs
        outside the store lock (pwrite/pread are thread-safe on a shared
        fd), so only cache bookkeeping is ever serialized."""
        lid = block_id.local_id
        with self._lock:
            ent = self._fds.get(lid)
            if ent is None:
                if self.readonly:
                    flags = os.O_RDONLY
                else:
                    flags = os.O_RDWR | (os.O_CREAT if create else 0)
                ent = _CachedFd(os.open(self.block_path(block_id), flags))
                self._fds[lid] = ent
                # evict idle LRU entries past the cap; pinned entries are
                # skipped (the cache may transiently exceed the cap while
                # many blocks are mid-IO)
                idle = [k for k, e in self._fds.items() if e.refs == 0
                        and k != lid]
                for k in idle[: max(0, len(self._fds) - _FD_CACHE_CAP)]:
                    self._close_entry(self._fds.pop(k))
            else:
                self._fds.move_to_end(lid)
            ent.refs += 1
            return ent

    def _release(self, ent: _CachedFd) -> None:
        with self._lock:
            ent.refs -= 1
            if ent.evicted and ent.refs == 0:
                self._close_entry(ent)

    @staticmethod
    def _close_entry(ent: _CachedFd) -> None:
        if ent.fd >= 0:
            try:
                os.close(ent.fd)
            except OSError:  # ozlint: allow[error-swallowing] -- best-effort fd-cache eviction
                pass
            ent.fd = -1

    def _drop_fd(self, local_id: int) -> None:
        """Caller must hold self._lock."""
        ent = self._fds.pop(local_id, None)
        if ent is not None:
            if ent.refs == 0:
                self._close_entry(ent)
            else:
                ent.evicted = True  # last _release closes it

    def close(self) -> None:
        """Release every cached descriptor (container close/delete)."""
        with self._lock:
            for lid in list(self._fds):
                self._drop_fd(lid)

    # ------------------------------------------------------------- chunk IO
    def write_chunk(
        self, block_id: BlockID, info: ChunkInfo, data: np.ndarray | bytes,
        sync: bool = False,
    ) -> None:
        if self.readonly:
            raise StorageError(
                IO_EXCEPTION, f"write {info.name}: store is readonly")
        # zero-copy: bytes/bytearray already support the buffer protocol;
        # ndarrays go through memoryview IFF contiguous uint8 (the hot
        # path), else one normalizing copy
        if isinstance(data, (bytes, bytearray, memoryview)):
            try:
                view = memoryview(data).cast("B")
            except (TypeError, ValueError):
                # non-contiguous / structured memoryview: normalize
                view = memoryview(bytes(data))
        else:
            arr = np.asarray(data)
            if arr.dtype != np.uint8 or not arr.flags.c_contiguous:
                arr = np.ascontiguousarray(arr, dtype=np.uint8)
            view = memoryview(arr.reshape(-1))
        if len(view) != info.length:
            raise StorageError(
                INVALID_WRITE_SIZE,
                f"chunk {info.name}: data {len(view)} != declared "
                f"{info.length}",
            )
        try:
            ent = self._acquire(block_id, create=True)
        except OSError as e:
            raise StorageError(
                IO_EXCEPTION, f"write {self.block_path(block_id)}: {e}"
            ) from e
        try:
            written = 0
            while written < len(view):
                written += os.pwrite(ent.fd, view[written:],
                                     info.offset + written)
            if sync:
                os.fsync(ent.fd)
        except OSError as e:
            raise StorageError(
                IO_EXCEPTION, f"write {self.block_path(block_id)}: {e}"
            ) from e
        finally:
            self._release(ent)

    def read_chunk(self, block_id: BlockID, info: ChunkInfo) -> np.ndarray:
        try:
            ent = self._acquire(block_id, create=False)
        except OSError as e:
            raise StorageError(
                IO_EXCEPTION, f"read {self.block_path(block_id)}: {e}"
            ) from e
        try:
            buf = os.pread(ent.fd, info.length, info.offset)
        except OSError as e:
            raise StorageError(
                IO_EXCEPTION, f"read {self.block_path(block_id)}: {e}"
            ) from e
        finally:
            self._release(ent)
        if len(buf) < info.length:
            # short read: chunk may extend past written data (padding
            # semantics handled by the caller); zero-fill the tail
            buf = buf + b"\x00" * (info.length - len(buf))
        return np.frombuffer(buf, dtype=np.uint8).copy()

    def block_length(self, block_id: BlockID) -> int:
        path = self.block_path(block_id)
        return path.stat().st_size if path.exists() else 0

    def delete_block(self, block_id: BlockID) -> None:
        with self._lock:
            self._drop_fd(block_id.local_id)
        path = self.block_path(block_id)
        if path.exists():
            path.unlink()

    def fsync_block(self, block_id: BlockID) -> None:
        with self._lock:
            ent = self._fds.get(block_id.local_id)
            if ent is not None:
                ent.refs += 1
        if ent is not None:
            try:
                os.fsync(ent.fd)
            finally:
                self._release(ent)
            return
        path = self.block_path(block_id)
        if path.exists():
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
