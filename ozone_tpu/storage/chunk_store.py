"""Chunk IO: file-per-block layout with offset writes.

Mirrors the reference datanode's default chunk layout strategy
(container-service keyvalue/impl/FilePerBlockStrategy.java:69 — one file
per block, chunks written at their block offset) and ChunkUtils
(keyvalue/helpers/ChunkUtils.java: writeData:109-156 with overwrite
validation :285, readData:190-283). Durability via explicit flush+fsync on
commit rather than per-write.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np

from ozone_tpu.storage.ids import (
    INVALID_WRITE_SIZE,
    IO_EXCEPTION,
    BlockID,
    ChunkInfo,
    StorageError,
)


class FilePerBlockStore:
    """Chunks of a block live in one file `<chunks_dir>/<local_id>.block`."""

    def __init__(self, chunks_dir: Path, readonly: bool = False):
        self.chunks_dir = Path(chunks_dir)
        if not readonly:
            self.chunks_dir.mkdir(parents=True, exist_ok=True)

    def block_path(self, block_id: BlockID) -> Path:
        return self.chunks_dir / f"{block_id.local_id}.block"

    def write_chunk(
        self, block_id: BlockID, info: ChunkInfo, data: np.ndarray | bytes,
        sync: bool = False,
    ) -> None:
        data = np.asarray(
            np.frombuffer(data, dtype=np.uint8) if isinstance(data, (bytes, bytearray))
            else data,
            dtype=np.uint8,
        ).reshape(-1)
        if data.size != info.length:
            raise StorageError(
                INVALID_WRITE_SIZE,
                f"chunk {info.name}: data {data.size} != declared {info.length}",
            )
        path = self.block_path(block_id)
        try:
            with open(path, "r+b" if path.exists() else "w+b") as f:
                f.seek(info.offset)
                f.write(data.tobytes())
                if sync:
                    f.flush()
                    os.fsync(f.fileno())
        except OSError as e:
            raise StorageError(IO_EXCEPTION, f"write {path}: {e}") from e

    def read_chunk(self, block_id: BlockID, info: ChunkInfo) -> np.ndarray:
        path = self.block_path(block_id)
        try:
            with open(path, "rb") as f:
                f.seek(info.offset)
                buf = f.read(info.length)
        except OSError as e:
            raise StorageError(IO_EXCEPTION, f"read {path}: {e}") from e
        if len(buf) < info.length:
            # short read: chunk may extend past written data (padding
            # semantics handled by the caller); zero-fill the tail
            buf = buf + b"\x00" * (info.length - len(buf))
        return np.frombuffer(buf, dtype=np.uint8).copy()

    def block_length(self, block_id: BlockID) -> int:
        path = self.block_path(block_id)
        return path.stat().st_size if path.exists() else 0

    def delete_block(self, block_id: BlockID) -> None:
        path = self.block_path(block_id)
        if path.exists():
            path.unlink()

    def fsync_block(self, block_id: BlockID) -> None:
        path = self.block_path(block_id)
        if path.exists():
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
