"""Containers and volumes: the datanode storage engine.

Mirrors the reference's KeyValueContainer model (container-service
keyvalue/: a container is a directory with a descriptor + chunk files,
block metadata in a per-volume DB — schema V3 "one RocksDB per volume",
reference doc dn-merge-rocksdb). Here: one sqlite DB per volume holding
block metadata for all containers on that volume, a JSON descriptor per
container (ContainerDataYaml analog), and FilePerBlockStore chunk files.
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
import time
from pathlib import Path
from typing import Iterator, Optional

from ozone_tpu.storage.chunk_store import FilePerBlockStore
from ozone_tpu.storage.ids import (
    BLOCK_WRITE_CONFLICT,
    CONTAINER_EXISTS,
    CONTAINER_NOT_FOUND,
    INVALID_CONTAINER_STATE,
    NO_SUCH_BLOCK,
    BlockData,
    BlockID,
    ContainerState,
    StorageError,
)

log = logging.getLogger(__name__)


def _guard_sqlite(fn):
    """Surface a failing disk as StorageError(IO_EXCEPTION) instead of a
    raw sqlite3 error (the reference maps RocksDB failures to
    StorageContainerException): daemon RPC guards, the writers'
    exclude-and-reallocate handlers, and the volume-failure sweep all
    key off StorageError, and in-process callers (minicluster, embedded
    use) must see the same contract as the wire."""
    import functools

    @functools.wraps(fn)
    def inner(*a, **kw):
        try:
            return fn(*a, **kw)
        except sqlite3.Error as e:
            raise StorageError("IO_EXCEPTION", f"container db: {e}")

    return inner


class VolumeDB:
    """Per-volume block-metadata store (schema V3 analog). With
    readonly=True the sqlite file opens in mode=ro and no DDL runs —
    the offline debug tools can inspect a failing disk remounted
    read-only without writing a byte."""

    @_guard_sqlite
    def __init__(self, path: Path, readonly: bool = False):
        self._path = path
        self._lock = threading.Lock()
        if readonly:
            self._conn = sqlite3.connect(
                f"file:{path}?mode=ro", uri=True,
                check_same_thread=False)
            return
        self._conn = sqlite3.connect(str(path), check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS blocks ("
            " container_id INTEGER, local_id INTEGER, data TEXT,"
            " PRIMARY KEY (container_id, local_id))"
        )
        self._conn.execute("PRAGMA journal_mode=WAL")
        # WAL + NORMAL: block-metadata commits stop paying an fsync per
        # putBlock — the reference datanode's container DB writes with
        # RocksDB default WriteOptions (sync=false) the same way. WAL
        # keeps every committed txn across a PROCESS crash (the chaos
        # suite's kill -9); only an OS/power crash can drop the tail,
        # where the SCM's replica accounting repairs from peers.
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.commit()

    @_guard_sqlite
    def put_block(self, block: BlockData) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO blocks VALUES (?, ?, ?)",
                (
                    block.block_id.container_id,
                    block.block_id.local_id,
                    json.dumps(block.to_json()),
                ),
            )
            self._conn.commit()

    @_guard_sqlite
    def get_block(self, block_id: BlockID) -> Optional[BlockData]:
        with self._lock:
            row = self._conn.execute(
                "SELECT data FROM blocks WHERE container_id=? AND local_id=?",
                (block_id.container_id, block_id.local_id),
            ).fetchone()
        return BlockData.from_json(json.loads(row[0])) if row else None

    @_guard_sqlite
    def list_blocks(self, container_id: int) -> list[BlockData]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT data FROM blocks WHERE container_id=? ORDER BY local_id",
                (container_id,),
            ).fetchall()
        return [BlockData.from_json(json.loads(r[0])) for r in rows]

    @_guard_sqlite
    def delete_block(self, block_id: BlockID) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM blocks WHERE container_id=? AND local_id=?",
                (block_id.container_id, block_id.local_id),
            )
            self._conn.commit()

    @_guard_sqlite
    def delete_container(self, container_id: int) -> None:
        with self._lock:
            self._conn.execute(
                "DELETE FROM blocks WHERE container_id=?", (container_id,)
            )
            self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class Container:
    """One container replica on one volume."""

    def __init__(
        self,
        container_id: int,
        root: Path,
        db: VolumeDB,
        state: ContainerState = ContainerState.OPEN,
        replica_index: int = 0,
        readonly: bool = False,
    ):
        self.id = container_id
        self.root = Path(root)
        self.db = db
        self.state = state
        self.replica_index = replica_index
        self.created_at = time.time()
        self.chunks = FilePerBlockStore(self.root / "chunks",
                                        readonly=readonly)
        self._lock = threading.RLock()
        # write fence (ChunkUtils.validateChunkForOverwrite analog,
        # keyvalue/helpers/ChunkUtils.java:285-312): first identified
        # writer to touch a block file owns it until the block is
        # deleted; a DIFFERENT writer's stream is refused instead of
        # interleaving two keys' bytes in one chunk file. In-memory by
        # design — the commit-first SCM allocator is the primary
        # guarantee, this is defense in depth within one process life.
        self._block_writers: dict[int, str] = {}

    # -- descriptor (ContainerDataYaml analog) --
    def _descriptor_path(self) -> Path:
        return self.root / "container.json"

    def save_descriptor(self) -> None:
        self._descriptor_path().write_text(
            json.dumps(
                {
                    "id": self.id,
                    "state": self.state.value,
                    "replica_index": self.replica_index,
                    "created_at": self.created_at,
                }
            )
        )

    @classmethod
    def load(cls, root: Path, db: VolumeDB,
             readonly: bool = False) -> "Container":
        d = json.loads((Path(root) / "container.json").read_text())
        c = cls(
            int(d["id"]),
            root,
            db,
            ContainerState(d["state"]),
            int(d.get("replica_index", 0)),
            readonly=readonly,
        )
        c.created_at = d.get("created_at", c.created_at)
        return c

    # -- state machine --
    def require_writable(self) -> None:
        if self.state not in (ContainerState.OPEN, ContainerState.RECOVERING):
            raise StorageError(
                INVALID_CONTAINER_STATE,
                f"container {self.id} is {self.state.value}, not writable",
            )

    def close(self) -> None:
        with self._lock:
            if self.state in (ContainerState.CLOSED, ContainerState.QUASI_CLOSED):
                return
            if self.state not in (
                ContainerState.OPEN,
                ContainerState.CLOSING,
                ContainerState.RECOVERING,
            ):
                raise StorageError(
                    INVALID_CONTAINER_STATE,
                    f"cannot close container {self.id} in {self.state.value}",
                )
            self.state = ContainerState.CLOSED
            self.save_descriptor()
            # no more writes can land: reclaim the fence map
            self._block_writers.clear()

    def mark_unhealthy(self) -> None:
        with self._lock:
            self.state = ContainerState.UNHEALTHY
            self.save_descriptor()

    def bind_writer(self, block_id: BlockID, writer: Optional[str]) -> None:
        """Enforce single-writer ownership of a block file. Anonymous
        callers (writer=None: repair/replication/offline tools) bypass
        the fence — every client write path supplies an identity."""
        if writer is None:
            return
        with self._lock:
            cur = self._block_writers.get(block_id.local_id)
            if cur is None:
                self._block_writers[block_id.local_id] = writer
            elif cur != writer:
                raise StorageError(
                    BLOCK_WRITE_CONFLICT,
                    f"{block_id} is being written by {cur!r}; refusing "
                    f"interleaved stream from {writer!r}",
                )

    def release_writer(self, block_id: BlockID) -> None:
        with self._lock:
            self._block_writers.pop(block_id.local_id, None)

    # -- block ops --
    def put_block(self, block: BlockData) -> None:
        self.db.put_block(block)

    def get_block(self, block_id: BlockID) -> BlockData:
        b = self.db.get_block(block_id)
        if b is None:
            raise StorageError(NO_SUCH_BLOCK, str(block_id))
        return b

    def list_blocks(self) -> list[BlockData]:
        return self.db.list_blocks(self.id)

    def used_bytes(self) -> int:
        return sum(b.length for b in self.list_blocks())


class HddsVolume:
    """One storage volume (disk) holding container directories + a VolumeDB."""

    _PROBE = b"ozone-tpu-disk-check"

    def __init__(self, root: Path, readonly: bool = False):
        self.root = Path(root)
        if not readonly:
            (self.root / "containers").mkdir(parents=True, exist_ok=True)
        self.db = VolumeDB(self.root / "metadata.db", readonly=readonly)
        self.readonly = readonly
        #: a failed disk (StorageVolumeChecker verdict): excluded from
        #: placement, its replicas dropped from the container set
        self.failed = False

    def check(self) -> bool:
        """Disk health probe (the reference's DiskChecker behind
        StorageVolumeChecker): a tiny write/read/delete round-trip in
        the volume root. Any OSError — or a readback mismatch, the
        silent-corruption face of a dying disk — marks the volume
        failed. A failed verdict is sticky, like the reference's
        failed-volume set."""
        if self.failed:
            return False
        probe = self.root / ".disk-check"
        try:
            probe.write_bytes(self._PROBE)
            ok = probe.read_bytes() == self._PROBE
            probe.unlink()
            if not ok:
                raise OSError("disk probe readback mismatch")
            return True
        except OSError:
            log.warning("volume %s failed its disk check", self.root)
            self.failed = True
            return False

    def container_dir(self, container_id: int) -> Path:
        return self.root / "containers" / str(container_id)

    def load_containers(self, on_error=None) -> Iterator[Container]:
        """Yield this volume's containers. With `on_error` set, a
        container that fails to load (crash-truncated descriptor, bad
        permissions) is reported through the callback and skipped
        instead of aborting the iteration — the forensic-tool contract;
        without it, errors propagate (a serving datanode must not
        silently drop replicas)."""
        cdir = self.root / "containers"
        if not cdir.is_dir():
            return
        for d in sorted(cdir.iterdir()):
            if not (d / "container.json").exists():
                continue
            if on_error is None:
                yield Container.load(d, self.db, readonly=self.readonly)
                continue
            try:
                yield Container.load(d, self.db, readonly=self.readonly)
            except Exception as e:  # noqa: BLE001 - reported, not fatal
                on_error(f"{d}: bad descriptor: {e}")

    def close(self) -> None:
        self.db.close()


class ContainerSet:
    """All container replicas on one datanode (reference common/impl/
    ContainerSet.java)."""

    def __init__(self):
        self._containers: dict[int, Container] = {}
        self._lock = threading.Lock()

    def add(self, c: Container, overwrite: bool = False) -> None:
        with self._lock:
            if not overwrite and c.id in self._containers:
                raise StorageError(CONTAINER_EXISTS, str(c.id))
            self._containers[c.id] = c

    def get(self, container_id: int) -> Container:
        c = self._containers.get(container_id)
        if c is None:
            raise StorageError(CONTAINER_NOT_FOUND, str(container_id))
        return c

    def get_or_none(self, container_id: int) -> Optional[Container]:
        return self._containers.get(container_id)

    def remove(self, container_id: int) -> None:
        with self._lock:
            self._containers.pop(container_id, None)

    def __iter__(self) -> Iterator[Container]:
        return iter(list(self._containers.values()))

    def __len__(self) -> int:
        return len(self._containers)
