"""Container export/import as tarballs.

Mirror of the reference's TarContainerPacker (container-service
keyvalue/TarContainerPacker.java, used by the DN->DN replication stream
GrpcReplicationService.java:51: a container replica travels as one packed
archive of descriptor + block metadata + chunk files), with optional gzip
compression (CopyContainerCompression analog).
"""

from __future__ import annotations

import io
import json
import tarfile
from typing import Optional

from ozone_tpu.storage.container import Container
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import BlockData, ContainerState, StorageError


def export_container(container: Container, compress: bool = False) -> bytes:
    """Pack a container replica: descriptor, block metadata, chunk files.

    Only writer-free replicas export — an OPEN container mid-write would
    snapshot torn chunks (the guard lives HERE so every transport shares
    it)."""
    from ozone_tpu.storage.ids import (
        INVALID_CONTAINER_STATE,
        ContainerState,
        StorageError,
    )

    if container.state not in (ContainerState.CLOSED,
                               ContainerState.QUASI_CLOSED):
        raise StorageError(
            INVALID_CONTAINER_STATE,
            f"container {container.id} is {container.state.value}; only "
            "closed replicas export (close it first)",
        )
    buf = io.BytesIO()
    mode = "w:gz" if compress else "w"
    with tarfile.open(fileobj=buf, mode=mode) as tar:
        desc = json.dumps(
            {
                "id": container.id,
                "replica_index": container.replica_index,
                "state": container.state.value,
            }
        ).encode()
        info = tarfile.TarInfo("container.json")
        info.size = len(desc)
        tar.addfile(info, io.BytesIO(desc))

        blocks = [b.to_json() for b in container.list_blocks()]
        meta = json.dumps(blocks).encode()
        info = tarfile.TarInfo("blocks.json")
        info.size = len(meta)
        tar.addfile(info, io.BytesIO(meta))

        for f in sorted(container.chunks.chunks_dir.glob("*.block")):
            tar.add(str(f), arcname=f"chunks/{f.name}")
    return buf.getvalue()


def import_container(dn: Datanode, data: bytes,
                     replica_index: Optional[int] = None,
                     expect_id: Optional[int] = None) -> Container:
    """Unpack a container replica onto a datanode; the imported replica
    lands CLOSED (import is only valid for closed/quasi-closed replicas,
    like the reference's import path). A failure after the RECOVERING
    container was created removes it — ONLY a container this import
    created; a pre-existing replica raising CONTAINER_EXISTS is never
    touched — so the import can be retried (the reference's cleanup of
    RECOVERING containers on reconstruction failure)."""
    buf = io.BytesIO(data)
    created: Optional[Container] = None
    try:
        with tarfile.open(fileobj=buf, mode="r:*") as tar:
            desc = json.loads(
                tar.extractfile("container.json").read().decode())
            if expect_id is not None and int(desc["id"]) != int(expect_id):
                # the caller's authorization (container token) named a
                # different container than the tarball carries
                raise StorageError(
                    "CONTAINER_ID_MISMATCH",
                    f"tarball is container {desc['id']}, not {expect_id}")
            blocks = json.loads(
                tar.extractfile("blocks.json").read().decode())
            created = dn.create_container(
                int(desc["id"]),
                replica_index=(
                    replica_index if replica_index is not None
                    else int(desc.get("replica_index", 0))
                ),
                state=ContainerState.RECOVERING,
            )
            c = created
            for member in tar.getmembers():
                if member.name.startswith("chunks/") and member.isfile():
                    dest = c.chunks.chunks_dir / member.name[len("chunks/"):]
                    with open(dest, "wb") as out:
                        out.write(tar.extractfile(member).read())
            for b in blocks:
                c.put_block(BlockData.from_json(b))
            c.close()
        return c
    except Exception:
        if created is not None:
            try:
                dn.delete_container(created.id, force=True)
            except Exception:  # noqa: BLE001 - best-effort cleanup
                pass
        raise
