"""Container export/import as tarballs.

Mirror of the reference's TarContainerPacker (container-service
keyvalue/TarContainerPacker.java, used by the DN->DN replication stream
GrpcReplicationService.java:51: a container replica travels as one packed
archive of descriptor + block metadata + chunk files), with a negotiated
compression matrix (CopyContainerCompression.java analog: the reference
offers no_compression/gzip/lz4/snappy/zstd; here every codec importable
in this interpreter is offered — zstd and lz4 when their modules exist,
gzip and none always). Import never needs the name on the wire: each
codec's frame magic identifies it, so mixed-version peers interoperate
by construction; a peer that RECEIVES a codec it cannot decompress
raises UNSUPPORTED_COMPRESSION and the sender retries with gzip.
"""

from __future__ import annotations

import io
import json
import tarfile
from typing import Optional

from ozone_tpu.storage.container import Container
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import BlockData, ContainerState, StorageError

UNSUPPORTED_COMPRESSION = "UNSUPPORTED_COMPRESSION"

_ZSTD_MAGIC = b"\x28\xb5\x2f\xfd"
_LZ4_MAGIC = b"\x04\x22\x4d\x18"
_GZIP_MAGIC = b"\x1f\x8b"

#: preference order when negotiating (reference default is no_compression;
#: operators pick — we prefer the best available ratio/speed codec)
CODEC_PREFERENCE = ("zstd", "lz4", "gzip", "none")


def _zstd():
    try:
        import zstandard

        return zstandard
    except ImportError:
        return None


def _lz4():
    try:
        import lz4.frame

        return lz4.frame
    except ImportError:
        return None


def available_codecs() -> tuple[str, ...]:
    """Codecs THIS interpreter can both compress and decompress."""
    out = []
    if _zstd() is not None:
        out.append("zstd")
    if _lz4() is not None:
        out.append("lz4")
    out.extend(["gzip", "none"])
    return tuple(out)


def negotiate_codec(accept) -> str:
    """First mutually-available codec in preference order; `accept` is
    the peer's offered list (missing/empty -> gzip, the pre-matrix wire
    default)."""
    accept = [a for a in (accept or []) if a]
    if not accept:
        return "gzip"
    ours = set(available_codecs())
    for name in CODEC_PREFERENCE:
        if name in ours and name in accept:
            return name
    return "none" if "none" in accept else "gzip"


def compress_blob(name: str, data: bytes) -> bytes:
    if name == "none":
        return data
    if name == "gzip":
        import gzip

        return gzip.compress(data, compresslevel=1)
    if name == "zstd":
        z = _zstd()
        if z is None:
            raise StorageError(UNSUPPORTED_COMPRESSION, "zstd unavailable")
        return z.ZstdCompressor().compress(data)
    if name == "lz4":
        l4 = _lz4()
        if l4 is None:
            raise StorageError(UNSUPPORTED_COMPRESSION, "lz4 unavailable")
        return l4.compress(data)
    raise StorageError(UNSUPPORTED_COMPRESSION, f"unknown codec {name}")


def sniff_decompress(data: bytes) -> bytes:
    """Identify the codec by frame magic and decompress; plain tar (or
    gzip, which tarfile handles natively) passes through."""
    if data[:4] == _ZSTD_MAGIC:
        z = _zstd()
        if z is None:
            raise StorageError(
                UNSUPPORTED_COMPRESSION,
                "peer sent zstd; this node cannot decompress it")
        return z.ZstdDecompressor().decompress(
            data, max_output_size=2 ** 32)
    if data[:4] == _LZ4_MAGIC:
        l4 = _lz4()
        if l4 is None:
            raise StorageError(
                UNSUPPORTED_COMPRESSION,
                "peer sent lz4; this node cannot decompress it")
        return l4.decompress(data)
    return data  # plain tar or gzip (tarfile r:* handles gzip)


def export_container(container: Container, compress: bool = False,
                     compression: Optional[str] = None) -> bytes:
    """Pack a container replica: descriptor, block metadata, chunk files.

    `compression` names a codec from the matrix (zstd/lz4/gzip/none);
    the legacy `compress` bool means gzip. Only writer-free replicas
    export — an OPEN container mid-write would snapshot torn chunks
    (the guard lives HERE so every transport shares it)."""
    from ozone_tpu.storage.ids import (
        INVALID_CONTAINER_STATE,
        ContainerState,
        StorageError,
    )

    if container.state not in (ContainerState.CLOSED,
                               ContainerState.QUASI_CLOSED):
        raise StorageError(
            INVALID_CONTAINER_STATE,
            f"container {container.id} is {container.state.value}; only "
            "closed replicas export (close it first)",
        )
    codec = compression if compression is not None else (
        "gzip" if compress else "none")
    buf = io.BytesIO()
    # gzip keeps the tarfile-native framing (old peers read it); the
    # matrix codecs wrap a plain tar
    mode = "w:gz" if codec == "gzip" else "w"
    with tarfile.open(fileobj=buf, mode=mode) as tar:
        desc = json.dumps(
            {
                "id": container.id,
                "replica_index": container.replica_index,
                "state": container.state.value,
            }
        ).encode()
        info = tarfile.TarInfo("container.json")
        info.size = len(desc)
        tar.addfile(info, io.BytesIO(desc))

        blocks = [b.to_json() for b in container.list_blocks()]
        meta = json.dumps(blocks).encode()
        info = tarfile.TarInfo("blocks.json")
        info.size = len(meta)
        tar.addfile(info, io.BytesIO(meta))

        for f in sorted(container.chunks.chunks_dir.glob("*.block")):
            tar.add(str(f), arcname=f"chunks/{f.name}")
    out = buf.getvalue()
    if codec in ("none", "gzip"):
        return out
    return compress_blob(codec, out)


def import_container(dn: Datanode, data: bytes,
                     replica_index: Optional[int] = None,
                     expect_id: Optional[int] = None) -> Container:
    """Unpack a container replica onto a datanode; the imported replica
    lands CLOSED (import is only valid for closed/quasi-closed replicas,
    like the reference's import path). A failure after the RECOVERING
    container was created removes it — ONLY a container this import
    created; a pre-existing replica raising CONTAINER_EXISTS is never
    touched — so the import can be retried (the reference's cleanup of
    RECOVERING containers on reconstruction failure)."""
    buf = io.BytesIO(sniff_decompress(data))
    created: Optional[Container] = None
    try:
        with tarfile.open(fileobj=buf, mode="r:*") as tar:
            desc = json.loads(
                tar.extractfile("container.json").read().decode())
            if expect_id is not None and int(desc["id"]) != int(expect_id):
                # the caller's authorization (container token) named a
                # different container than the tarball carries
                raise StorageError(
                    "CONTAINER_ID_MISMATCH",
                    f"tarball is container {desc['id']}, not {expect_id}")
            blocks = json.loads(
                tar.extractfile("blocks.json").read().decode())
            created = dn.create_container(
                int(desc["id"]),
                replica_index=(
                    replica_index if replica_index is not None
                    else int(desc.get("replica_index", 0))
                ),
                state=ContainerState.RECOVERING,
            )
            c = created
            for member in tar.getmembers():
                if member.name.startswith("chunks/") and member.isfile():
                    dest = c.chunks.chunks_dir / member.name[len("chunks/"):]
                    with open(dest, "wb") as out:
                        out.write(tar.extractfile(member).read())
            for b in blocks:
                c.put_block(BlockData.from_json(b))
            c.close()
        return c
    except Exception:
        if created is not None:
            try:
                dn.delete_container(created.id, force=True)
            except Exception:  # ozlint: allow[error-swallowing] -- best-effort cleanup of the half-imported container; the original error re-raises below
                pass
        raise
