"""Datanode: volumes + container set + request dispatcher.

The dispatcher's verb surface mirrors DatanodeClientProtocol.proto's Type
enum (:82-110 — CreateContainer, WriteChunk, PutBlock, GetBlock, ReadChunk,
ListBlock, CloseContainer, GetCommittedBlockLength, ...) dispatched the way
HddsDispatcher -> KeyValueHandler does it (container-service
keyvalue/KeyValueHandler.java verb switch :247-288). In-process API now;
the gRPC server wraps these methods 1:1.

Also hosts the container data scanner (BackgroundContainerDataScanner
analog, ozoneimpl/): full-chunk checksum verification that marks
containers UNHEALTHY — a natural TPU batch job via the device CRC kernel.
"""

from __future__ import annotations

import itertools
import logging
import threading
from pathlib import Path
from typing import Optional

import numpy as np

from ozone_tpu.storage.container import Container, ContainerSet, HddsVolume
from ozone_tpu.storage.ids import (
    CHECKSUM_MISMATCH,
    CLOSED_CONTAINER_IO,
    BlockData,
    BlockID,
    ChunkInfo,
    ContainerState,
    StorageError,
)
from ozone_tpu.utils.checksum import Checksum, ChecksumError
from ozone_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)


class Datanode:
    """One datanode instance over a root directory of volumes."""

    def __init__(self, root: Path, dn_id: str = "dn0",
                 num_volumes: int = 1,
                 volume_policy: str = "round-robin"):
        self.root = Path(root)
        self.id = dn_id
        self.volume_policy = volume_policy
        self.volumes = [
            HddsVolume(self.root / f"vol{i}") for i in range(num_volumes)
        ]
        self.containers = ContainerSet()
        #: bumped on every container/block mutation — heartbeats send a
        #: full container report only when this moved (or periodically),
        #: the reference's ICR-on-change + periodic-FCR cadence; building
        #: a full report walks every container's block table, far too
        #: expensive to do per heartbeat on an idle node
        self.mutation_count = 0
        self.metrics = MetricsRegistry(f"datanode.{dn_id}")
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._scan_requests: set[int] = set()
        for vol in self.volumes:
            for c in vol.load_containers():
                self.containers.add(c)

    # -- volume choice (reference VolumeChoosingPolicy SPI):
    # "round-robin" = RoundRobinVolumeChoosingPolicy (default),
    # "capacity" = CapacityVolumeChoosingPolicy — new containers land
    # on the least-used volume so disks fill evenly under skew
    def _volume_used(self, vol: HddsVolume) -> int:
        # volume identity via the shared VolumeDB handle — a path
        # prefix test would alias vol1 with vol10..vol19
        return sum(c.used_bytes() for c in self.containers
                   if c.db is vol.db)

    def _choose_volume(self) -> HddsVolume:
        healthy = [v for v in self.volumes if not v.failed]
        if not healthy:
            raise StorageError("IO_EXCEPTION",
                               f"{self.id}: no healthy volumes left")
        if len(healthy) > 1 and self.volume_policy == "capacity":
            # one pass over the containers, not one per volume
            used = {id(v.db): 0 for v in healthy}
            for c in self.containers:
                k = id(c.db)
                if k in used:
                    used[k] += c.used_bytes()
            return min(healthy, key=lambda v: used[id(v.db)])
        return healthy[next(self._rr) % len(healthy)]

    def check_volumes(self) -> list[str]:
        """StorageVolumeChecker sweep: probe every volume; a newly
        failed volume's container replicas are dropped from the set —
        the next full report omits them, the SCM's replica accounting
        sees the loss, and the replication manager repairs elsewhere
        (the reference's VolumeSet failed-volume flow)."""
        newly_failed: list[str] = []
        for vol in self.volumes:
            if vol.failed or vol.check():
                continue
            newly_failed.append(str(vol.root))
            # sweep under the same lock create_container holds for its
            # choose->add window: a create that chose this volume before
            # the verdict has either finished (its container is in the
            # set and gets dropped here) or has not started choosing
            # (it will see vol.failed) — no replica can slip through
            with self._lock:
                lost = [c for c in self.containers if c.db is vol.db]
                for c in lost:
                    self.containers.remove(c.id)
                self.mutation_count += 1
            self.metrics.counter("volumes_failed").inc()
            log.warning("%s: volume %s failed; dropped %d container "
                        "replicas", self.id, vol.root, len(lost))
        return newly_failed

    @property
    def healthy_volume_count(self) -> int:
        return sum(1 for v in self.volumes if not v.failed)

    # -- container verbs --
    def create_container(
        self,
        container_id: int,
        replica_index: int = 0,
        state: ContainerState = ContainerState.OPEN,
    ) -> Container:
        with self._lock:
            vol = self._choose_volume()
            c = Container(
                container_id,
                vol.container_dir(container_id),
                vol.db,
                state=state,
                replica_index=replica_index,
            )
            c.root.mkdir(parents=True, exist_ok=True)
            c.save_descriptor()
            self.containers.add(c)
            self.mutation_count += 1
            self.metrics.counter("container_created").inc()
            return c

    def get_container(self, container_id: int) -> Container:
        return self.containers.get(container_id)

    def close_container(self, container_id: int) -> None:
        self.containers.get(container_id).close()
        self.mutation_count += 1
        self.metrics.counter("container_closed").inc()

    def delete_container(self, container_id: int, force: bool = False) -> None:
        c = self.containers.get(container_id)
        if not force and c.state == ContainerState.OPEN:
            raise StorageError(
                CLOSED_CONTAINER_IO, f"container {container_id} is OPEN"
            )
        c.db.delete_container(container_id)
        c.chunks.close()  # release cached block-file descriptors
        for b in list(c.chunks.chunks_dir.glob("*.block")):
            b.unlink()
        if c.root.exists():
            import shutil

            shutil.rmtree(c.root, ignore_errors=True)
        self.containers.remove(container_id)
        self.mutation_count += 1
        self.metrics.counter("container_deleted").inc()

    def list_containers(self) -> list[Container]:
        return list(self.containers)

    # -- chunk/block verbs --
    def write_chunk(
        self, block_id: BlockID, info: ChunkInfo, data, sync: bool = False,
        writer: Optional[str] = None,
    ) -> None:
        with self.metrics.histogram("chunk_write_seconds").time():
            c = self.containers.get(block_id.container_id)
            c.require_writable()
            self._fence(c, block_id, writer)
            c.chunks.write_chunk(block_id, info, data, sync=sync)
            self.mutation_count += 1
            self.metrics.counter("bytes_written").inc(info.length)

    def _fence(self, container, block_id: BlockID,
               writer: Optional[str]) -> None:
        """Single-writer block fence (validateChunkForOverwrite analog).
        A violation means SOMEONE attempted a duplicate-id write — the
        refusal protects the first writer's bytes, and the container
        gets an on-demand verification scan (the reference's
        OnDemandContainerDataScanner trigger-on-error pattern)."""
        try:
            container.bind_writer(block_id, writer)
        except StorageError:
            self.metrics.counter("write_fence_violations").inc()
            self.request_scan(container.id)
            raise

    # -- on-demand scan queue (drained by the daemon's scanner loop) --
    def request_scan(self, container_id: int) -> None:
        with self._lock:
            self._scan_requests.add(int(container_id))

    def pop_scan_requests(self) -> list[int]:
        with self._lock:
            out = sorted(self._scan_requests)
            self._scan_requests.clear()
            return out

    def read_chunk(
        self, block_id: BlockID, info: ChunkInfo, verify: bool = False
    ) -> np.ndarray:
        with self.metrics.histogram("chunk_read_seconds").time():
            c = self.containers.get(block_id.container_id)
            data = c.chunks.read_chunk(block_id, info)
            if verify and info.checksum.checksums:
                try:
                    Checksum().verify(data, info.checksum,
                                      offset_hint=str(block_id))
                except ChecksumError as e:
                    self.metrics.counter("checksum_failures").inc()
                    self.on_read_error(c)
                    raise StorageError(CHECKSUM_MISMATCH, str(e)) from e
            self.metrics.counter("bytes_read").inc(info.length)
            return data

    def put_block(self, block: BlockData, sync: bool = False,
                  writer: Optional[str] = None) -> None:
        c = self.containers.get(block.block_id.container_id)
        c.require_writable()
        # same fence as the data path: a foreign writer must not commit
        # its chunk list over a block another writer owns
        self._fence(c, block.block_id, writer)
        if sync:
            c.chunks.fsync_block(block.block_id)
        block.committed = True
        c.put_block(block)
        self.mutation_count += 1
        self.metrics.counter("blocks_committed").inc()

    def get_block(self, block_id: BlockID) -> BlockData:
        return self.containers.get(block_id.container_id).get_block(block_id)

    def list_blocks(self, container_id: int) -> list[BlockData]:
        return self.containers.get(container_id).list_blocks()

    def get_committed_block_length(self, block_id: BlockID) -> int:
        return self.get_block(block_id).length

    def delete_block(self, block_id: BlockID) -> None:
        c = self.containers.get(block_id.container_id)
        c.db.delete_block(block_id)
        c.chunks.delete_block(block_id)
        c.release_writer(block_id)
        self.mutation_count += 1

    # -- scanners --
    def on_read_error(self, container: Container) -> None:
        """On-demand scan trigger (OnDemandContainerDataScanner analog)."""
        # conservative: a checksum failure marks the container unhealthy;
        # the SCM-side ReplicationManager will re-replicate/reconstruct.
        container.mark_unhealthy()
        self.mutation_count += 1

    def scan_container(self, container_id: int) -> list[str]:
        """Full-data scan: verify every chunk checksum
        (BackgroundContainerDataScanner analog). Returns error strings and
        marks the container UNHEALTHY if any."""
        c = self.containers.get(container_id)
        errors: list[str] = []
        for block in c.list_blocks():
            for info in block.chunks:
                try:
                    data = c.chunks.read_chunk(block.block_id, info)
                    if info.checksum.checksums:
                        Checksum().verify(data, info.checksum)
                except (StorageError, ChecksumError) as e:
                    errors.append(f"{block.block_id}/{info.name}: {e}")
        if errors:
            c.mark_unhealthy()
        self.metrics.counter("containers_scanned").inc()
        return errors

    def container_report(self) -> list[dict]:
        """Per-container replica report for SCM heartbeats (reference ICR/FCR
        container reports in ScmServerDatanodeHeartbeatProtocol.proto)."""
        return [
            {
                "container_id": c.id,
                "state": c.state.value,
                "replica_index": c.replica_index,
                "block_count": len(c.list_blocks()),
                "used_bytes": c.used_bytes(),
            }
            for c in self.containers
        ]

    def close(self) -> None:
        for c in self.containers:
            c.chunks.close()
        for v in self.volumes:
            v.close()
