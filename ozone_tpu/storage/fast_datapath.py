"""Datapath sidecar host: the C++ hot path wired into a Datanode.

The native listener (native/datapath.cpp) owns the per-chunk work —
frame parse, pwrite/pread, CRC32C verify, fsync — while this module
keeps the CONTROL PLANE in Python via three per-stream callbacks:

- auth: token verification (BlockTokenVerifier), layout gate
  (RequestFeatureValidator analog for the batched verb), container
  writability, the single-writer fence, and block-file path resolution.
- done: the piggybacked block commit (``Datanode.put_block``) plus the
  stream/chunk/byte metrics the gRPC verbs maintain.
- fail: read-side checksum failure -> mark the container unhealthy
  (OnDemandContainerDataScanner trigger analog).

Per-chunk semantics match the gRPC verbs byte-for-byte: same file-per-
block layout (``FilePerBlockStore.block_path``), same zero-fill short
reads, same fsync-before-commit discipline (the C++ side fsyncs on a
sync stream before the commit callback runs, so ``put_block`` is handed
already-durable bytes). Role analog of the reference's native-epoll
Netty transport + ChunkUtils mapped IO (GrpcXceiverService.java:42,
ChunkUtils.java:109-156) — the Python interpreter leaves the per-chunk
path entirely.
"""

from __future__ import annotations

import ctypes
import json
import logging
import threading
from typing import Optional

from ozone_tpu.native import build_shared, _HERE
from ozone_tpu.storage.ids import (
    BLOCK_TOKEN_VERIFICATION_FAILED,
    BlockData,
    BlockID,
    StorageError,
)

log = logging.getLogger(__name__)

_SRC = _HERE / "datapath.cpp"
_SO = _HERE / "libdatapath.so"

_AUTH_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
    ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)
_DONE_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32,
    ctypes.c_int32, ctypes.c_uint64, ctypes.c_uint32,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)
_FAIL_CB = ctypes.CFUNCTYPE(
    None, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32)

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def load_lib() -> Optional[ctypes.CDLL]:
    """Build-on-demand + load (native/__init__ pattern); None when no
    toolchain — the daemon then simply serves gRPC only."""
    global _lib, _lib_tried
    with _lib_lock:
        if _lib is not None or _lib_tried:
            return _lib
        _lib_tried = True
        so = build_shared(_SRC, _SO,
                          extra=("-O3", "-march=native", "-std=c++17",
                                 "-pthread"))
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(str(so))
            lib.dp_start.restype = ctypes.c_void_p
            lib.dp_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                     _AUTH_CB, _DONE_CB, _FAIL_CB]
            lib.dp_port.restype = ctypes.c_int
            lib.dp_port.argtypes = [ctypes.c_void_p]
            lib.dp_uds.restype = ctypes.c_int
            lib.dp_uds.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int]
            lib.dp_stop.argtypes = [ctypes.c_void_p]
            lib.dp_crc32c.restype = ctypes.c_uint32
            lib.dp_crc32c.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            # buffer-pool capsule API (native arena lease/release)
            lib.dp_buf_lease.restype = ctypes.c_void_p
            lib.dp_buf_lease.argtypes = [ctypes.c_uint64]
            lib.dp_buf_data.restype = ctypes.c_void_p
            lib.dp_buf_data.argtypes = [ctypes.c_void_p]
            lib.dp_buf_cap.restype = ctypes.c_uint64
            lib.dp_buf_cap.argtypes = [ctypes.c_void_p]
            lib.dp_buf_retain.argtypes = [ctypes.c_void_p]
            lib.dp_buf_release.argtypes = [ctypes.c_void_p]
            lib.dp_pool_stat.restype = ctypes.c_uint64
            lib.dp_pool_stat.argtypes = [ctypes.c_int]
            _lib = lib
        except OSError as e:
            log.warning("native datapath unavailable: %s", e)
            _lib = None
        return _lib


def native_pool_stats() -> Optional[dict]:
    """Arena counters from the C++ side of the pool (the Python half
    lives in codec/hostmem.py). None without the native toolchain."""
    lib = load_lib()
    if lib is None:
        return None
    return {
        "leased_bytes": int(lib.dp_pool_stat(0)),
        "free_bytes": int(lib.dp_pool_stat(1)),
        "high_water_bytes": int(lib.dp_pool_stat(2)),
    }


def _pack_out(out, cap: int, ok: bool, body: bytes) -> int:
    n = 1 + len(body)
    if n > cap:
        return -1
    out[0] = 1 if ok else 0
    if body:
        ctypes.memmove(ctypes.addressof(out.contents) + 1, body, len(body))
    return n


def _error_body(code: str, message: str) -> bytes:
    return json.dumps({"error": {"code": code, "message": message}}).encode()


class DatapathSidecar:
    """One native listener per datanode process."""

    def __init__(self, dn, verifier=None, layout=None,
                 host: str = "127.0.0.1", port: int = 0):
        self.dn = dn
        self.verifier = verifier
        self.layout = layout
        self.host = host
        self._want_port = port
        self.port: Optional[int] = None
        #: abstract unix socket name ("@...") for the co-located lane;
        #: None when the native side could not set one up
        self.uds: Optional[str] = None
        self._handle = None
        # CFUNCTYPE wrappers must outlive the listener (GC'd callbacks
        # are a segfault from a C++ thread)
        self._cbs = (_AUTH_CB(self._auth), _DONE_CB(self._done),
                     _FAIL_CB(self._fail))

    # ------------------------------------------------------------ callbacks
    def _hdr(self, hdr, hdr_len: int) -> dict:
        return json.loads(ctypes.string_at(hdr, hdr_len))

    def _auth(self, hdr, hdr_len, is_write, out, out_cap) -> int:
        try:
            m = self._hdr(hdr, hdr_len)
            block_id = BlockID.from_json(m["block_id"])
            if is_write:
                self._gate_layout()
                self._check_token(m, block_id, "WRITE")
                c = self.dn.containers.get(block_id.container_id)
                c.require_writable()
                if c.chunks.readonly:
                    raise StorageError("IO_EXCEPTION", "store is readonly")
                self.dn._fence(c, block_id, m.get("writer"))
            else:
                self._check_token(m, block_id, "READ")
                c = self.dn.containers.get(block_id.container_id)
            return _pack_out(out, out_cap, True,
                             str(c.chunks.block_path(block_id)).encode())
        except StorageError as e:
            return _pack_out(out, out_cap, False,
                             _error_body(e.code, e.msg))
        except Exception as e:  # noqa: BLE001 - must never unwind into C++
            log.exception("datapath auth failed")
            return _pack_out(out, out_cap, False,
                             _error_body("IO_EXCEPTION", str(e)))

    def _done(self, hdr, hdr_len, is_write, nbytes, nchunks,
              out, out_cap) -> int:
        try:
            m = self._hdr(hdr, hdr_len)
            block_id = BlockID.from_json(m["block_id"])
            mx = self.dn.metrics
            if is_write:
                mx.counter("batched_write_streams").inc()
                mx.counter("batched_write_chunks").inc(int(nchunks))
                mx.counter("bytes_written").inc(int(nbytes))
                self.dn.mutation_count += 1
                commit = m.get("commit")
                if commit is not None:
                    bd = BlockData.from_json(commit)
                    if bd.block_id != block_id:
                        raise StorageError(
                            "INVALID_ARGUMENT",
                            f"commit names {bd.block_id}, stream wrote "
                            f"{block_id}")
                    # sync streams were fsynced by the native side
                    # before this callback: put_block gets
                    # already-durable bytes, so sync=False
                    self.dn.put_block(bd, sync=False,
                                      writer=m.get("writer"))
            else:
                mx.counter("batched_read_streams").inc()
                mx.counter("batched_read_chunks").inc(int(nchunks))
                mx.counter("bytes_read").inc(int(nbytes))
            return _pack_out(out, out_cap, True, b"")
        except StorageError as e:
            return _pack_out(out, out_cap, False,
                             _error_body(e.code, e.msg))
        except Exception as e:  # noqa: BLE001 - must never unwind into C++
            log.exception("datapath commit failed")
            return _pack_out(out, out_cap, False,
                             _error_body("IO_EXCEPTION", str(e)))

    def _fail(self, hdr, hdr_len) -> None:
        try:
            m = self._hdr(hdr, hdr_len)
            block_id = BlockID.from_json(m["block_id"])
            c = self.dn.containers.get(block_id.container_id)
            self.dn.metrics.counter("checksum_failures").inc()
            self.dn.on_read_error(c)
        except Exception:  # noqa: BLE001 - must never unwind into C++
            log.exception("datapath fail-report failed")

    def _gate_layout(self) -> None:
        """Native writes are the batched verb: same layout gate as
        WriteChunksCommit (the client's single-chunk write_chunk falls
        back to the ungated gRPC verb on this refusal)."""
        if self.layout is None:
            return
        from ozone_tpu.utils.upgrade import (
            PRE_FINALIZE_ERROR,
            RATIS_STREAMING_WRITE,
        )

        if not self.layout.is_allowed(RATIS_STREAMING_WRITE):
            raise StorageError(
                PRE_FINALIZE_ERROR,
                f"native datapath needs layout feature "
                f"{RATIS_STREAMING_WRITE.name} "
                f"(v{RATIS_STREAMING_WRITE.version}); datanode is at "
                f"layout {self.layout.metadata_version}")

    def _check_token(self, m: dict, block_id: BlockID, mode: str) -> None:
        if self.verifier is None or not self.verifier.enabled:
            return
        from ozone_tpu.utils.security import AccessMode, TokenError

        try:
            self.verifier.verify(m.get("token"), block_id, AccessMode(mode))
        except TokenError as e:
            raise StorageError(BLOCK_TOKEN_VERIFICATION_FAILED, str(e))

    # ------------------------------------------------------------ lifecycle
    def start(self) -> Optional[int]:
        lib = load_lib()
        if lib is None:
            return None
        self._handle = lib.dp_start(self.host.encode(), self._want_port,
                                    *self._cbs)
        if not self._handle:
            log.warning("native datapath failed to bind %s:%d",
                        self.host, self._want_port)
            return None
        self.port = lib.dp_port(self._handle)
        buf = ctypes.create_string_buffer(128)
        n = lib.dp_uds(self._handle, buf, len(buf))
        self.uds = buf.raw[:n].decode() if n > 0 else None
        log.info("native datapath listening on %s:%d uds=%s (dn=%s)",
                 self.host, self.port, self.uds, self.dn.id)
        return self.port

    def advertise(self) -> dict:
        """GetDatapathInfo payload: TCP port plus the abstract unix
        socket a co-located client should prefer."""
        return {"port": self.port, "uds": self.uds}

    def stop(self) -> None:
        if self._handle is not None:
            load_lib().dp_stop(self._handle)
            self._handle = None
            self.port = None
            self.uds = None
