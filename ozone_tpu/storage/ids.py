"""Core storage identifiers and metadata records.

Mirrors the reference's id model: containers hold blocks, blocks hold
chunks (README.md "Ozone consists of volumes, buckets, and keys" +
container/block/chunk hierarchy in hadoop-hdds). BlockID = (container_id,
local_id) as in hdds ContainerBlockID; EC adds a per-container replica
index (hdds.proto ECReplicationConfig/replicaIndex usage).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Optional

from ozone_tpu.utils.checksum import ChecksumData, ChecksumType


@dataclass(frozen=True, order=True)
class BlockID:
    container_id: int
    local_id: int

    def __str__(self) -> str:
        return f"blk_{self.container_id}_{self.local_id}"

    def to_json(self) -> dict:
        return {"container_id": self.container_id, "local_id": self.local_id}

    @classmethod
    def from_json(cls, d: dict) -> "BlockID":
        return cls(int(d["container_id"]), int(d["local_id"]))


class ContainerState(Enum):
    """Container lifecycle (DatanodeClientProtocol.proto:256-264 State enum;
    SCM-side lifecycle OPEN->CLOSING->QUASI_CLOSED/CLOSED->DELETED in
    server-scm ContainerStateManagerImpl)."""

    OPEN = "OPEN"
    CLOSING = "CLOSING"
    QUASI_CLOSED = "QUASI_CLOSED"
    CLOSED = "CLOSED"
    UNHEALTHY = "UNHEALTHY"
    INVALID = "INVALID"
    DELETED = "DELETED"
    RECOVERING = "RECOVERING"


@dataclass(frozen=True)
class ChunkInfo:
    """One chunk of a block: name, offset within the block, length, checksum
    (reference ContainerProtos ChunkInfo message)."""

    name: str
    offset: int
    length: int
    checksum: ChecksumData = field(
        default_factory=lambda: ChecksumData(ChecksumType.NONE, 0)
    )

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "offset": self.offset,
            "length": self.length,
            "checksum": self.checksum.to_lists(),
        }

    @classmethod
    def from_json(cls, d: dict) -> "ChunkInfo":
        return cls(
            d["name"],
            int(d["offset"]),
            int(d["length"]),
            ChecksumData.from_lists(d["checksum"]),
        )


@dataclass
class BlockData:
    """Block metadata stored in the container DB: chunk list + total length
    (reference container keyvalue BlockData / BlockManagerImpl.java:54)."""

    block_id: BlockID
    chunks: list[ChunkInfo] = field(default_factory=list)
    # length of the logical block group this block belongs to (EC putBlock
    # carries blockGroupLength, ECBlockOutputStream.java:103-195)
    block_group_length: Optional[int] = None
    committed: bool = False

    @property
    def length(self) -> int:
        return sum(c.length for c in self.chunks)

    def to_json(self) -> dict:
        return {
            "block_id": self.block_id.to_json(),
            "chunks": [c.to_json() for c in self.chunks],
            "block_group_length": self.block_group_length,
            "committed": self.committed,
        }

    @classmethod
    def from_json(cls, d: dict) -> "BlockData":
        return cls(
            BlockID.from_json(d["block_id"]),
            [ChunkInfo.from_json(c) for c in d["chunks"]],
            d.get("block_group_length"),
            bool(d.get("committed", False)),
        )


class StorageError(Exception):
    """Dispatcher-level error with a result code mirroring
    DatanodeClientProtocol.proto Result enum."""

    def __init__(self, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}" if msg else code)
        self.code = code
        self.msg = msg  # bare message for re-wrapping without code stacking


# Result codes (subset of DatanodeClientProtocol.proto Result)
CONTAINER_NOT_FOUND = "CONTAINER_NOT_FOUND"
CONTAINER_EXISTS = "CONTAINER_EXISTS"
NO_SUCH_BLOCK = "NO_SUCH_BLOCK"
CHECKSUM_MISMATCH = "CHECKSUM_MISMATCH"
CLOSED_CONTAINER_IO = "CLOSED_CONTAINER_IO"
INVALID_CONTAINER_STATE = "INVALID_CONTAINER_STATE"
IO_EXCEPTION = "IO_EXCEPTION"
INVALID_WRITE_SIZE = "INVALID_WRITE_SIZE"
# a second writer tried to stream into a block file another writer owns
# (ChunkUtils.validateChunkForOverwrite analog, ChunkUtils.java:285-312):
# defense in depth under the commit-first SCM allocator — a duplicate
# (container, local_id) can no longer be ISSUED, and even if one were,
# the datanode refuses to interleave two writers' bytes
BLOCK_WRITE_CONFLICT = "BLOCK_WRITE_CONFLICT"
# refused block/container capability token (BlockTokenVerifier.java);
# shared by the gRPC datapath and the Ratis submit surface
BLOCK_TOKEN_VERIFICATION_FAILED = "BLOCK_TOKEN_VERIFICATION_FAILED"
