"""Datanode Raft write path: pipeline consensus over the container store.

Role analog of the reference's XceiverServerRatis + ContainerStateMachine
(container-service common/transport/server/ratis/XceiverServerRatis.java
:124 — one Raft server per datanode hosting one Raft group per pipeline;
ContainerStateMachine.java:126 — two-phase writes where chunk payloads are
persisted off the Raft log proper in writeStateMachineData:519 and
applyTransaction commits only metadata).

The data/metadata split here follows the reference's *streaming* write
pipeline (docs feature/Streaming-Write-Pipeline.md, Ratis DataStream API,
survey #34): chunk BYTES travel over the plain gRPC datapath to every
pipeline member (zero re-encode, never entering the consensus log), while
the ORDERING and COMMIT of those writes go through the pipeline's Raft
group — create/writeChunk-commit/putBlock/close verbs are proposed to the
leader, replicated, and applied on every member. apply validates that the
member actually holds the bytes the committed metadata describes (length
probe; content checksums are the scanners' job, as in the reference where
applyTransaction trusts the writeStateMachineData phase); a member that
missed the data phase fails the apply, marks the container unhealthy, and
is repaired by the SCM replication manager — the same containment the
reference uses when writeStateMachineData fails on a follower.

Snapshots carry only the applied-index marker, exactly like
ContainerStateMachine.takeSnapshot:341 (container data is node-local and
durable; a peer resurrected past the compaction horizon re-syncs through
container replication, not the raft log).
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from typing import Optional

from ozone_tpu.consensus.raft import (
    NotRaftLeaderError,
    RaftConfig,
    RaftNode,
)
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import BlockData, BlockID, StorageError

log = logging.getLogger(__name__)


class ContainerStateMachine:
    """Applies committed pipeline verbs to the local container store."""

    def __init__(self, dn: Datanode):
        self.dn = dn

    def apply(self, data: dict) -> dict:
        verb = data.get("verb")
        if verb == "create_container":
            try:
                self.dn.create_container(
                    int(data["container_id"]),
                    replica_index=int(data.get("replica_index", 0)),
                )
            except StorageError as e:
                if e.code != "CONTAINER_EXISTS":  # idempotent re-apply
                    raise
            return {"ok": True}
        if verb == "write_chunk_commit":
            return self._apply_write_chunk(data)
        if verb == "put_block":
            block = BlockData.from_json(data["block"])
            self.dn.put_block(block, sync=bool(data.get("sync", False)),
                              writer=data.get("writer"))
            return {"ok": True, "committed_length": block.length}
        if verb == "close_container":
            self.dn.close_container(int(data["container_id"]))
            return {"ok": True}
        raise StorageError("UNSUPPORTED_REQUEST", f"verb {verb!r}")

    def _apply_write_chunk(self, data: dict) -> dict:
        """Commit point of a chunk: the bytes must already be local (data
        phase); validate extent, never content (scanner territory)."""
        bid = BlockID.from_json(data["block_id"])
        offset = int(data["offset"])
        length = int(data["length"])
        c = self.dn.containers.get(bid.container_id)
        c.require_writable()
        have = c.chunks.block_length(bid)
        if have < offset + length:
            # this member missed the data phase (down/partitioned during
            # the stream): poison the replica, let replication repair it
            c.mark_unhealthy()
            raise StorageError(
                "CHUNK_DATA_MISSING",
                f"{bid} has {have} bytes locally, commit needs "
                f"{offset + length}",
            )
        return {"ok": True}

    # ------------------------------------------------------- snapshotting
    def snapshot(self) -> dict:
        # applied-index marker only (ContainerStateMachine.takeSnapshot
        # analog); container contents are already durable on disk
        return {"marker": "container-sm"}

    def restore(self, data) -> None:  # noqa: ARG002 - marker only
        return


class RatisXceiverServer:
    """Hosts one RaftNode per pipeline this datanode serves.

    The XceiverServerRatis analog: `join` creates/loads the group for a
    pipeline (SCM's create-pipeline command path), `submit` is the
    client-facing ordered write entry point (leader only), `watch` is
    watchForCommit (XceiverClientRatis.watchForCommit:297 — block until
    the write is applied on ALL members, or a MAJORITY).
    """

    def __init__(self, dn: Datanode, root: Path, node_address: str,
                 rpc_service=None, tls=None,
                 config: RaftConfig = RaftConfig(),
                 auto_timers: bool = True):
        self.dn = dn
        self.root = Path(root)
        self.node_address = node_address
        self.rpc_service = rpc_service  # net/raft_transport.RaftRpcService
        self.tls = tls
        self.config = config
        #: False = tests drive elections/heartbeats deterministically via
        #: tick()/start_election() (the reference's no-real-clock style)
        self.auto_timers = auto_timers
        self._groups: dict[str, RaftNode] = {}
        self._transports: dict[str, object] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ groups
    def group_id(self, pipeline_id: int) -> str:
        return f"pipeline-{pipeline_id}"

    def join(self, pipeline_id: int, peers: dict[str, str],
             transport=None) -> RaftNode:
        """Join (or re-join after restart) a pipeline's raft group.

        `peers` maps every member datanode id -> RpcServer address
        (including this node). A grpc transport is built unless an
        explicit transport (tests: InProcessTransport) is given.
        """
        gid = self.group_id(pipeline_id)
        with self._lock:
            node = self._groups.get(gid)
            if node is not None:
                same = set(node.peer_ids) | {self.dn.id} == set(peers)
                if same:
                    if transport is None and gid in self._transports:
                        for pid, addr in peers.items():
                            self._transports[gid].set_peer(pid, addr)
                    return node
                # defense in depth: a served group with different
                # membership is stale (ids are persisted and never
                # reused, so this only happens on metadata loss) —
                # replace it rather than mis-address the new pipeline
                log.warning(
                    "%s: group %s membership changed %s -> %s; replacing",
                    self.dn.id, gid,
                    sorted({*node.peer_ids, self.dn.id}), sorted(peers))
                self._stop_group_locked(gid)
                import shutil

                shutil.rmtree(self.root / "ratis" / gid,
                              ignore_errors=True)
                node = None
            if transport is None:
                from ozone_tpu.net.raft_transport import GrpcRaftTransport

                transport = GrpcRaftTransport(gid, dict(peers), tls=self.tls,
                                              owner=self.dn.id)
                self._transports[gid] = transport
            sm = ContainerStateMachine(self.dn)
            node = RaftNode(
                node_id=self.dn.id,
                peer_ids=[p for p in peers if p != self.dn.id],
                storage_dir=self.root / "ratis" / gid,
                apply_fn=sm.apply,
                snapshot_fn=sm.snapshot,
                restore_fn=sm.restore,
                config=self.config,
                transport=transport,
                metrics_name=f"raft.{self.dn.id}.{gid}",
            )
            self._groups[gid] = node
            if self.rpc_service is not None:
                self.rpc_service.register(gid, node)
            if self.auto_timers:
                node.start_timers()
            return node

    def _stop_group_locked(self, gid: str) -> None:
        node = self._groups.pop(gid, None)
        tr = self._transports.pop(gid, None)
        if node is not None:
            node.stop()
            if self.rpc_service is not None:
                self.rpc_service.unregister(gid)
        if tr is not None and hasattr(tr, "close"):
            tr.close()

    def leave(self, pipeline_id: int) -> None:
        with self._lock:
            self._stop_group_locked(self.group_id(pipeline_id))

    def get(self, pipeline_id: int) -> Optional[RaftNode]:
        with self._lock:
            return self._groups.get(self.group_id(pipeline_id))

    def pipelines(self) -> list[str]:
        with self._lock:
            return list(self._groups)

    # ----------------------------------------------------------- serving
    def submit(self, pipeline_id: int, request: dict,
               timeout: float = 30.0) -> dict:
        """Propose a pipeline verb on the local node (must be leader)."""
        node = self.get(pipeline_id)
        if node is None:
            raise StorageError("NO_SUCH_RAFT_GROUP",
                               f"pipeline {pipeline_id} not served here")
        try:
            result = node.propose(request, timeout=timeout)
        except NotRaftLeaderError as e:
            raise StorageError(
                "NOT_LEADER", e.leader_hint or ""
            ) from e
        except TimeoutError as e:
            raise StorageError("TIMEOUT", str(e)) from e
        if isinstance(result, Exception):
            if isinstance(result, StorageError):
                raise result
            raise StorageError("IO_EXCEPTION", str(result))
        return {"index": node.last_applied, **(result or {})}

    def watch(self, pipeline_id: int, index: int, policy: str = "ALL",
              timeout: float = 30.0) -> dict:
        """watchForCommit: block until `index` is APPLIED on ALL members
        (majority already held — propose() returned). Uses the apply
        watermark followers report in append responses, so a successful
        ALL watch means the write's effects are visible on every replica."""
        node = self.get(pipeline_id)
        if node is None:
            raise StorageError("NO_SUCH_RAFT_GROUP",
                               f"pipeline {pipeline_id} not served here")
        deadline = time.monotonic() + timeout
        while True:
            if not node.is_leader:
                raise StorageError("NOT_LEADER", node.leader_hint or "")
            if node._timer_thread is None:
                node.tick()  # deterministic mode: push commit + collect acks
            applied = [node.applied_index.get(p, 0) >= index
                       for p in node.peer_ids]
            if policy == "MAJORITY":
                need = (len(node.peer_ids) + 1) // 2  # +self = quorum
                done = sum(applied) >= need and node.last_applied >= index
            else:
                done = all(applied) and node.last_applied >= index
            if done:
                return {"index": index, "policy": policy}
            if time.monotonic() >= deadline:
                raise StorageError(
                    "TIMEOUT",
                    f"watch({index}, {policy}) on pipeline {pipeline_id}")
            if node._timer_thread is not None:
                time.sleep(0.01)

    def leader_of(self, pipeline_id: int) -> Optional[str]:
        node = self.get(pipeline_id)
        if node is None:
            return None
        return node.node_id if node.is_leader else node.leader_hint

    def stop(self) -> None:
        with self._lock:
            groups = list(self._groups.values())
            transports = list(self._transports.values())
            self._groups.clear()
            self._transports.clear()
        for n in groups:
            n.stop()
        for t in transports:
            if hasattr(t, "close"):
                t.close()
