"""Offline EC reconstruction coordinator.

Mirrors the reference's ECReconstructionCoordinator flow (container-service
ec/reconstruction/ECReconstructionCoordinator.java:81-97 flow doc,
reconstructECContainerGroup:146): driven by an SCM ReconstructECContainers
command carrying source replica-index->node and target index->node maps
(server-scm ECUnderReplicationHandler.processAndSendCommands:107), the
executing datanode

  1. lists blocks on the source nodes,
  2. creates RECOVERING containers on the targets,
  3. per block: recovers the missing units' cells from any k survivors
     (ECBlockReconstructedStripeInputStream.recoverChunks analog — here a
     depth-1 pipeline of batched device decodes: batch N's recovered
     chunks stream to the targets while batch N+1 reads survivors and
     decodes on device),
  4. putBlock + closeContainer on the targets,
  5. on any failure deletes the RECOVERING containers (:193-220).

TPU-first: decode+CRC of recovered cells happen in one fused device pass;
recovered chunks carry device-computed checksums.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from ozone_tpu.client import resilience
from ozone_tpu.client.dn_client import (
    DatanodeClientFactory,
    build_chunk_pairs,
    write_unit_stream,
)
from ozone_tpu.client.ec_reader import ECBlockGroupReader, unit_true_lengths
from ozone_tpu.client.ec_writer import BlockGroup
from ozone_tpu.codec.api import CoderOptions
from ozone_tpu.codec.fused import effective_bpc
from ozone_tpu.scm.pipeline import Pipeline, ReplicationConfig
from ozone_tpu.storage.ids import (
    BlockData,
    ChunkInfo,
    ContainerState,
    StorageError,
)
from ozone_tpu.utils.checksum import Checksum, ChecksumType
from ozone_tpu.utils.metrics import MetricsRegistry

log = logging.getLogger(__name__)

MISSING_NODE = "__missing__"


@dataclass(frozen=True)
class ReconstructionCommand:
    """SCM -> DN command (ReconstructECContainersCommand analog)."""

    container_id: int
    replication: CoderOptions
    sources: dict[int, str]  # replica index (1-based) -> dn_id
    targets: dict[int, str]  # missing replica index (1-based) -> dn_id


class ECReconstructionCoordinator:
    def __init__(
        self,
        clients: DatanodeClientFactory,
        checksum: ChecksumType = ChecksumType.CRC32C,
        bytes_per_checksum: int = 16 * 1024,
        mesh=None,
        use_ring: bool = False,
        max_parallel_blocks: int = 2,
        executor=None,
    ):
        self.clients = clients
        self.checksum = checksum
        self.bpc = bytes_per_checksum
        #: blocks of a container group repair in flight at once — each
        #: block's read+decode+write chain is independent, so a small
        #: pool overlaps one block's survivor reads with another's
        #: target writes (memory-bounded: each holds its cell batch)
        self.max_parallel_blocks = max(1, int(max_parallel_blocks))
        #: device mesh for the decode: stripe-parallel (DP) by default,
        #: survivor-sharded ring (SP) with use_ring — the reference runs
        #: its codec inside this same repair flow
        #: (ECReconstructionCoordinator.java:98,146); here the flow is
        #: the one that owns the mesh
        self.mesh = mesh
        self.use_ring = use_ring
        #: persistent mesh executor (parallel/mesh_executor.py): decode
        #: batches from EVERY block and container this coordinator
        #: repairs join one submission queue and coalesce into
        #: full-width mesh dispatches — the fleet-storm datapath
        self.executor = executor
        self.metrics = MetricsRegistry("ec.reconstruction")
        #: shared peer health: source selection skips breaker-open
        #: peers while alternatives exist, and the reader's survivor
        #: choice/straggler hedging below rides the same registry
        self.health = getattr(clients, "health", None) \
            or resilience.default_registry()

    def reconstruct_container_group(self, cmd: ReconstructionCommand) -> None:
        # reconstruction-job boundary: one deadline (operator opt-in via
        # OZONE_TPU_OP_DEADLINE_S) covers listing, every block's
        # recover+write chain, and the target close/cleanup
        with resilience.start("reconstruction"):
            self._reconstruct_container_group(cmd)

    def _reconstruct_container_group(self,
                                     cmd: ReconstructionCommand) -> None:
        opts = cmd.replication
        n = opts.all_units
        targets = sorted(cmd.targets)
        created: list[tuple[str, int]] = []
        try:
            # 2. RECOVERING containers on targets
            for idx in targets:
                dn = cmd.targets[idx]
                self.clients.get(dn).create_container(
                    cmd.container_id,
                    replica_index=idx,
                    state=ContainerState.RECOVERING,
                )
                created.append((dn, idx))

            # 1. block list from any source
            blocks = self._list_blocks(cmd)

            # 3.-4. per block: recover + write + putBlock. Independent
            # chains run through a small pool so survivor reads of one
            # block overlap target writes of another; any failure fails
            # the group (RECOVERING cleanup below)
            if self.max_parallel_blocks > 1 and len(blocks) > 1:
                from concurrent.futures import ThreadPoolExecutor

                with ThreadPoolExecutor(
                        max_workers=self.max_parallel_blocks,
                        thread_name_prefix="ec-recon") as pool:
                    list(pool.map(
                        lambda bd: self._reconstruct_block(
                            cmd, bd, targets), blocks))
            else:
                for bd in blocks:
                    self._reconstruct_block(cmd, bd, targets)

            # close targets
            for idx in targets:
                self.clients.get(cmd.targets[idx]).close_container(
                    cmd.container_id
                )
            self.metrics.counter("groups_reconstructed").inc()
        except Exception:
            # 5. cleanup RECOVERING containers on failure
            for dn, _idx in created:
                try:
                    self.clients.get(dn).delete_container(
                        cmd.container_id, force=True
                    )
                except (StorageError, KeyError, OSError) as e:
                    log.warning("cleanup of %s on %s failed: %s",
                                cmd.container_id, dn, e)
            self.metrics.counter("groups_failed").inc()
            raise

    def _list_blocks(self, cmd: ReconstructionCommand) -> list[BlockData]:
        last_err: Exception | None = None
        # health-ordered: breaker-allowing, fastest-EWMA sources first;
        # a tripped source is still LAST-resort dialed rather than
        # failing the job when it is the only replica left
        for dn in self.health.preferred(
                [cmd.sources[idx] for idx in sorted(cmd.sources)]):
            try:
                return self.health.observe(
                    dn, self.clients.get(dn).list_blocks,
                    cmd.container_id)
            except (StorageError, KeyError, OSError) as e:
                last_err = e
        raise StorageError(
            "CONTAINER_NOT_FOUND",
            f"no source could list blocks for {cmd.container_id}: {last_err}",
        )

    def _group_for(self, cmd: ReconstructionCommand, bd: BlockData) -> BlockGroup:
        """Synthesize the block-group view from the command's source map;
        indexes with no live source get a placeholder node the client
        factory cannot resolve (treated as unavailable by the reader)."""
        opts = cmd.replication
        nodes = [
            cmd.sources.get(i + 1, MISSING_NODE) for i in range(opts.all_units)
        ]
        length = bd.block_group_length
        if length is None:
            raise StorageError(
                "NO_SUCH_BLOCK", f"block {bd.block_id} has no group length"
            )
        return BlockGroup(
            container_id=cmd.container_id,
            local_id=bd.block_id.local_id,
            pipeline=Pipeline(ReplicationConfig.from_ec(opts), nodes),
            length=length,
        )

    def _reconstruct_block(
        self, cmd: ReconstructionCommand, bd: BlockData, targets: list[int]
    ) -> None:
        opts = cmd.replication
        cell = opts.cell_size
        bpc = effective_bpc(cell, self.bpc)
        group = self._group_for(cmd, bd)
        reader = ECBlockGroupReader(
            group,
            opts,
            self.clients,
            checksum=self.checksum,
            bytes_per_checksum=bpc,
            mesh=self.mesh,
            use_ring=self.use_ring,
            qos_class="bulk",  # repair storms defer to interactive reads
            executor=self.executor,
        )
        target_units = [idx - 1 for idx in targets]  # 0-based unit indexes
        lengths = unit_true_lengths(group, opts)
        host_checksum = Checksum(self.checksum, bpc)

        # Streaming repair through the reader's depth-1 decode pipeline:
        # batch N's recovered chunks land on the targets while batch N+1
        # reads survivors and decodes on device (one device dispatch per
        # stripe batch). Chunk records are keyed by stripe so a
        # mid-stream recovery restart simply overwrites — the single
        # put_block commit per target below runs only after every batch
        # landed (same all-chunks-before-commit order as before).
        written: list[dict[int, ChunkInfo]] = [{} for _ in targets]
        for sb, (cells, crcs) in reader.recover_cells_iter(target_units):
            for ti, idx in enumerate(targets):
                u = idx - 1
                pairs = build_chunk_pairs(
                    group.block_id, sb, cells[:, ti], crcs[:, ti],
                    lengths[u], cell, bpc, self.checksum, host_checksum)
                for info, _ in pairs:
                    written[ti][info.offset // cell] = info
                if pairs:
                    # one batched stream per rebuilt unit per batch when
                    # the target serves it, per-chunk verbs against
                    # older/pre-finalize targets
                    write_unit_stream(
                        self.clients.get(cmd.targets[idx]),
                        group.block_id, pairs)

        for ti, idx in enumerate(targets):
            dn = self.clients.get(cmd.targets[idx])
            infos = [written[ti][s] for s in sorted(written[ti])]
            dn.put_block(BlockData(
                group.block_id, infos,
                block_group_length=group.length,
            ))
            self.metrics.counter("blocks_reconstructed").inc()
            self.metrics.counter("bytes_reconstructed").inc(
                sum(i.length for i in infos)
            )
