"""Device-batched container scrubbing: checksum verification as batched
CRC dispatches instead of a per-slice host loop.

Role analog of the reference's BackgroundContainerDataScanner
(container-service ozoneimpl/ — throttled full-chunk checksum verify that
marks containers UNHEALTHY and lets the SCM replication manager repair
them; it data-scans only closed containers, never ones with live
writers). TPU-first divergence: full bytes-per-checksum slices are
stacked into uint8 batches and verified by the same GF(2) CRC kernel the
write path uses (codec/crc_device.py) — a whole container becomes a few
device dispatches. Tails (short final slices) and non-CRC32C checksum
types fall back to the host path.

Only checksum MISMATCHES (and metadata inconsistencies) poison a
replica. A chunk that cannot be read is re-checked against the block
metadata first: if the block vanished, a concurrent deletion won the
race and the chunk is skipped — an I/O race must not trigger needless
re-replication.
"""

from __future__ import annotations

import logging
from typing import TYPE_CHECKING

import numpy as np

from ozone_tpu.storage.ids import ContainerState, StorageError
from ozone_tpu.utils.checksum import (
    Checksum,
    ChecksumError,
    ChecksumType,
    crc32c,
)

if TYPE_CHECKING:  # pragma: no cover
    from ozone_tpu.storage.datanode import Datanode

log = logging.getLogger(__name__)

#: container states whose data is stable enough to scrub (the reference
#: scanner's shouldScanData contract: no live writers)
SCANNABLE_STATES = (ContainerState.CLOSED, ContainerState.QUASI_CLOSED)


def _next_pow2(n: int) -> int:
    return 1 << (max(1, n) - 1).bit_length()


class DeviceScrubber:
    """Batched CRC32C verification over container contents.

    With a `mesh`, the slice batch is sharded over it (DP) so one scrub
    dispatch spreads across every chip — the scrub-side twin of the
    sharded reconstruction decode (parallel/sharded.py)."""

    def __init__(self, max_batch_bytes: int = 64 * 1024 * 1024,
                 mesh=None):
        self.max_batch_bytes = max_batch_bytes
        self.mesh = mesh
        self._fns: dict[int, object] = {}

    def _crc_fn(self, bpc: int):
        fn = self._fns.get(bpc)
        if fn is None:
            from ozone_tpu.codec.crc_device import make_crc_fn

            if self.mesh is None:
                fn = make_crc_fn(bpc)
            else:
                import jax
                from jax.sharding import (
                    NamedSharding,
                    PartitionSpec as P,
                )

                axis = self.mesh.axis_names[0]
                sharding = NamedSharding(self.mesh, P(axis))
                fn = jax.jit(make_crc_fn(bpc),
                             in_shardings=sharding,
                             out_shardings=sharding)
            self._fns[bpc] = fn
        return fn

    def _dispatch(self, bpc: int, bufs: list, exps: list, labels: list,
                  errors: list[str]) -> None:
        """Verify one slice batch on device and drain the buffers.

        Batches are padded to the next power of two (zero slices, results
        ignored) so the jitted kernel sees a handful of shapes per bpc
        instead of recompiling for every container's exact slice count.
        """
        import jax

        if not bufs:
            return
        n = len(bufs)
        padded = _next_pow2(n)
        if self.mesh is not None:
            # the sharded dim must divide by the mesh — which may be any
            # size (default_codec_mesh spans all local devices): round
            # the pow2 up to the next multiple of it
            m = self.mesh.devices.size
            padded += (-padded) % m
        batch = np.zeros((padded, bpc), dtype=np.uint8)
        batch[:n] = np.stack(bufs)
        crcs = np.asarray(
            jax.device_get(self._crc_fn(bpc)(batch))).reshape(-1)[:n]
        exp = np.asarray(exps, dtype=np.uint32)
        for i in np.nonzero(crcs != exp)[0][:64]:
            lbl, sl = labels[int(i)]
            errors.append(f"{lbl}: crc mismatch at slice {sl}")
        bufs.clear()
        exps.clear()
        labels.clear()

    def scrub_container(self, dn: "Datanode", container_id: int,
                        mark_unhealthy: bool = True) -> list[str]:
        """Verify every chunk checksum in a container; returns error
        strings and (by default) poisons the replica on any."""
        c = dn.containers.get(container_id)
        errors: list[str] = []
        # bpc -> (slice buffers, expected crcs, (label, slice idx));
        # drained to the device whenever a group reaches the batch cap so
        # peak host memory is bounded by max_batch_bytes per group, not
        # by the container size
        groups: dict[int, tuple[list, list, list]] = {}
        for block in c.list_blocks():
            for info in block.chunks:
                cd = info.checksum
                if not cd.checksums:
                    continue
                label = f"{block.block_id}/{info.name}"
                try:
                    data = np.asarray(
                        c.chunks.read_chunk(block.block_id, info),
                        dtype=np.uint8,
                    ).reshape(-1)
                except StorageError as e:
                    # corruption evidence only if the block metadata is
                    # still live; a concurrently deleted block is a race,
                    # not damage
                    if c.db.get_block(block.block_id) is not None:
                        errors.append(f"{label}: {e}")
                    continue
                if cd.type is not ChecksumType.CRC32C:
                    try:
                        Checksum().verify(data, cd, label)
                    except ChecksumError as e:
                        errors.append(f"{label}: {e}")
                    continue
                bpc = cd.bytes_per_checksum
                n_full = data.size // bpc
                expected_entries = n_full + (1 if data.size % bpc else 0)
                if len(cd.checksums) != expected_entries:
                    errors.append(
                        f"{label}: {len(cd.checksums)} checksum entries "
                        f"for {data.size} bytes (expected "
                        f"{expected_entries})")
                    continue
                bufs, exps, labels = groups.setdefault(bpc, ([], [], []))
                cap = max(1, self.max_batch_bytes // bpc)
                for i in range(n_full):
                    bufs.append(data[i * bpc:(i + 1) * bpc])
                    exps.append(int.from_bytes(cd.checksums[i], "big"))
                    labels.append((label, i))
                    if len(bufs) >= cap:
                        self._dispatch(bpc, bufs, exps, labels, errors)
                tail = data[n_full * bpc:]
                if tail.size:
                    if crc32c(tail).to_bytes(4, "big") \
                            != cd.checksums[n_full]:
                        errors.append(
                            f"{label}: crc mismatch at tail slice "
                            f"{n_full}")
        for bpc, (bufs, exps, labels) in groups.items():
            self._dispatch(bpc, bufs, exps, labels, errors)
        if errors and mark_unhealthy:
            c.mark_unhealthy()
        dn.metrics.counter("containers_scrubbed").inc()
        return errors

    def scrub_all(self, dn: "Datanode") -> dict[int, list[str]]:
        """One pass over every scannable (writer-free) container."""
        out: dict[int, list[str]] = {}
        for c in dn.list_containers():
            if c.state not in SCANNABLE_STATES:
                continue
            try:
                errs = self.scrub_container(dn, c.id)
            except StorageError as e:
                errs = [str(e)]
            if errs:
                out[c.id] = errs
        return out
