"""Chaos harness: random failure injection under load.

Mirror of the reference's mini-chaos-tests (fault-injection-test
OzoneChaosCluster + FailureManager: randomly restart/kill datanodes while
load generators run invariant checks). The FailureManager here stops and
restarts MiniOzoneCluster datanodes on a schedule while a load thread
writes keys; the invariant is that every key whose commit succeeded is
byte-exactly readable afterwards (EC tolerates p concurrent failures).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from ozone_tpu.testing.minicluster import MiniOzoneCluster

log = logging.getLogger(__name__)


@dataclass
class ChaosResult:
    keys_written: list[str] = field(default_factory=list)
    write_failures: int = 0
    kills: int = 0
    restarts: int = 0
    read_mismatches: list[str] = field(default_factory=list)
    read_errors: list[str] = field(default_factory=list)


class FailureManager:
    """Randomly stops/restarts datanodes, keeping at most `max_down` down
    (p for an EC cluster)."""

    def __init__(self, cluster: MiniOzoneCluster, max_down: int = 1,
                 seed: int = 0, interval_s: float = 0.3):
        self.cluster = cluster
        self.max_down = max_down
        self.rng = random.Random(seed)
        self.interval = interval_s
        self.down: list[str] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.kills = 0
        self.restarts = 0

    def _tick(self) -> None:
        if self.down and (len(self.down) >= self.max_down
                          or self.rng.random() < 0.5):
            dn = self.down.pop(self.rng.randrange(len(self.down)))
            self.cluster.restart_datanode(dn)
            self.restarts += 1
        else:
            alive = [
                d.id
                for d in self.cluster.datanodes
                if d.id not in self.down
            ]
            if len(alive) > 1:
                dn = self.rng.choice(alive)
                self.cluster.stop_datanode(dn)
                self.down.append(dn)
                self.kills += 1

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval):
                try:
                    self._tick()
                except Exception:
                    log.exception("failure manager tick failed")

        self._thread = threading.Thread(target=loop, name="failure-manager",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for dn in list(self.down):
            self.cluster.restart_datanode(dn)
        self.down.clear()


def run_chaos(
    cluster: MiniOzoneCluster,
    duration_s: float = 5.0,
    replication: str = "rs-3-2-4096",
    key_size: int = 20_000,
    max_down: int = 1,
    seed: int = 0,
) -> ChaosResult:
    """Write keys under random failures, then verify every committed key."""
    result = ChaosResult()
    oz = cluster.client()
    vol = oz.create_volume(f"chaos{seed}")
    bucket = vol.create_bucket("b", replication=replication)
    rng = np.random.default_rng(seed)
    fm = FailureManager(cluster, max_down=max_down, seed=seed)
    fm.start()

    deadline = time.time() + duration_s
    i = 0
    try:
        while time.time() < deadline:
            name = f"key-{i}"
            data = rng.integers(0, 256, key_size, dtype=np.uint8)
            # deterministic payload per key for later verification
            data[:8] = np.frombuffer(
                i.to_bytes(8, "big"), dtype=np.uint8
            )
            try:
                bucket.write_key(name, data)
                result.keys_written.append(name)
            except Exception as e:
                log.info("write %s failed under chaos: %s", name, e)
                result.write_failures += 1
            i += 1
    finally:
        fm.stop()
        result.kills = fm.kills
        result.restarts = fm.restarts

    # verification phase: cluster whole again, every committed key readable
    rng_v = np.random.default_rng(seed)
    for j in range(i):
        expect = rng_v.integers(0, 256, key_size, dtype=np.uint8)
        expect[:8] = np.frombuffer(j.to_bytes(8, "big"), dtype=np.uint8)
        name = f"key-{j}"
        if name not in result.keys_written:
            continue
        try:
            got = bucket.read_key(name)
            if not np.array_equal(got, expect):
                result.read_mismatches.append(name)
        except Exception as e:
            result.read_errors.append(f"{name}: {e}")
    return result
