"""Controller for the native filesystem failure injector.

The reference ships a standalone C++ gRPC service that interposes a
filesystem and corrupts/fails/delays operations under a datanode
(tools/fault-injection-service). This build's equivalent is an
LD_PRELOAD interposer (native/failure_injector.cpp) plus this
controller: rules are written to a file the shim re-reads on mtime
change, so faults can be planted, retargeted, and cleared on a *live*
process with no native RPC stack. Inject into any subprocess by merging
`env()` into its environment.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Optional

from ozone_tpu.native import build_shared

_HERE = Path(__file__).parent
_SRC = _HERE.parent / "native" / "failure_injector.cpp"
_SO = _HERE.parent / "native" / "libfailure_injector.so"


def build_injector() -> Optional[Path]:
    """Compile (once) and return the interposer .so, None if no
    toolchain — callers (tests) skip instead of failing."""
    return build_shared(_SRC, _SO, extra=("-ldl",))


class FaultInjector:
    """Plant filesystem faults for child processes.

    >>> fi = FaultInjector(tmp_path)
    >>> fi.fail("write", dn_root / "chunks", "EIO")
    >>> subprocess.run([...], env={**os.environ, **fi.env()})
    """

    def __init__(self, workdir: Path):
        self.rules_path = Path(workdir) / "fi_rules.txt"
        self.rules_path.write_text("")
        self._rules: list[str] = []
        self._last_mtime = int(self.rules_path.stat().st_mtime)

    # ------------------------------------------------------------- rules
    def _flush(self) -> None:
        self.rules_path.write_text("".join(self._rules))
        # the shim compares whole-second mtimes: every flush must land on
        # a strictly new time_t value or a same-second update would be
        # missed forever; bump monotonically past the last one
        st = self.rules_path.stat()
        self._last_mtime = max(int(st.st_mtime), self._last_mtime + 1)
        os.utime(self.rules_path, (st.st_atime, self._last_mtime))
        time.sleep(0)

    def fail(self, op: str, path_prefix, err: str = "EIO") -> None:
        self._rules.append(f"{op} {path_prefix} fail {err}\n")
        self._flush()

    def delay(self, op: str, path_prefix, millis: int) -> None:
        self._rules.append(f"{op} {path_prefix} delay {millis}\n")
        self._flush()

    def corrupt_writes(self, path_prefix) -> None:
        """Bit-flip the first byte of every matched write (the
        scanner/checksum-verification test hook)."""
        self._rules.append(f"write {path_prefix} corrupt\n")
        self._flush()

    def clear(self) -> None:
        self._rules = []
        self._flush()

    # ------------------------------------------------------------- env
    def env(self) -> dict[str, str]:
        so = build_injector()
        if so is None:
            raise RuntimeError("native toolchain unavailable")
        return {
            "LD_PRELOAD": str(so),
            "OZONE_FI_CONFIG": str(self.rules_path),
        }
