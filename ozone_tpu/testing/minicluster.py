"""MiniOzoneCluster analog: full in-process cluster for integration tests.

Mirrors the reference's MiniOzoneClusterImpl (integration-test
MiniOzoneClusterImpl.java — real OM + SCM + N datanodes in one process,
loopback transport): here a StorageContainerManager, an OzoneManager, and
N Datanodes wired through the in-process client factory, with a manual or
background heartbeat pump and a command-dispatch loop that executes SCM
commands (reconstruction, replica deletion) on the datanodes the way
DatanodeStateMachine's command handlers do.
"""

from __future__ import annotations

import logging
import threading
import time
from pathlib import Path
from typing import Optional

from ozone_tpu.client.dn_client import DatanodeClientFactory
from ozone_tpu.client.ozone_client import OzoneClient
from ozone_tpu.om.om import OzoneManager
from ozone_tpu.scm.replication_manager import (
    DeleteReplicaCommand,
    ReplicateCommand,
)
from ozone_tpu.scm.scm import StorageContainerManager
from ozone_tpu.storage.datanode import Datanode
from ozone_tpu.storage.ids import BlockData, BlockID, StorageError
from ozone_tpu.storage.reconstruction import (
    ECReconstructionCoordinator,
    ReconstructionCommand,
)

log = logging.getLogger(__name__)


class MiniOzoneCluster:
    def __init__(
        self,
        root: Path,
        num_datanodes: int = 5,
        racks: int = 1,
        block_size: int = 16 * 1024 * 1024,
        container_size: int = 256 * 1024 * 1024,
        stale_after_s: float = 9.0,
        dead_after_s: float = 30.0,
        placement_seed: Optional[int] = 42,
    ):
        self.root = Path(root)
        self.scm = StorageContainerManager(
            min_datanodes=min(num_datanodes, 1),
            container_size=container_size,
            placement_seed=placement_seed,
            stale_after_s=stale_after_s,
            dead_after_s=dead_after_s,
        )
        self.clients = DatanodeClientFactory()
        self.datanodes: list[Datanode] = []
        for i in range(num_datanodes):
            dn = Datanode(self.root / f"dn{i}", dn_id=f"dn{i}")
            self.datanodes.append(dn)
            self.clients.register_local(dn)
            rack = f"/rack{i % racks}" if racks > 1 else "/default-rack"
            self.scm.register_datanode(dn.id, rack=rack,
                                       capacity_bytes=10 * container_size)
        self.om = OzoneManager(
            self.root / "om" / "om.db",
            self.scm,
            clients=self.clients,
            block_size=block_size,
        )
        from ozone_tpu.parallel import mesh_executor
        from ozone_tpu.parallel.sharded import default_codec_mesh

        # repair decodes: the persistent mesh executor when it can
        # exist (coalesces batches across containers on long-lived
        # programs), else the raw DP mesh path
        ex = mesh_executor.maybe_executor()
        self.reconstruction = ECReconstructionCoordinator(
            self.clients,
            mesh=None if ex is not None else default_codec_mesh(),
            executor=ex)
        self._stopped_dns: set[str] = set()
        self._hb_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ----------------------------------------------------------------- client
    def client(self) -> OzoneClient:
        return OzoneClient(self.om, self.clients)

    def datanode(self, dn_id: str) -> Datanode:
        return next(d for d in self.datanodes if d.id == dn_id)

    # -------------------------------------------------------------- liveness
    def stop_datanode(self, dn_id: str) -> None:
        """Simulate a crash: stop heartbeating and unregister the client so
        IO to this node fails."""
        self._stopped_dns.add(dn_id)
        self.clients._local.pop(dn_id, None)

    def restart_datanode(self, dn_id: str) -> None:
        self._stopped_dns.discard(dn_id)
        self.clients.register_local(self.datanode(dn_id))

    # -------------------------------------------------------------- heartbeat
    def heartbeat_all(self, with_reports: bool = True) -> None:
        """One heartbeat round: every live DN reports and executes returned
        commands (DatanodeStateMachine heartbeat + command handler loop)."""
        for dn in self.datanodes:
            if dn.id in self._stopped_dns:
                continue
            report = dn.container_report() if with_reports else None
            commands = self.scm.heartbeat(dn.id, container_report=report)
            for cmd in commands:
                self._execute_command(dn, cmd)

    def _execute_command(self, dn: Datanode, cmd) -> None:
        from ozone_tpu.scm.block_deletion import DeleteBlocksCommand

        try:
            if isinstance(cmd, DeleteBlocksCommand):
                for bid in cmd.blocks:
                    try:
                        dn.delete_block(bid)
                    except StorageError:
                        pass
                self.scm.deleted_blocks.ack(dn.id, cmd.tx_ids)
            elif isinstance(cmd, ReconstructionCommand):
                self.reconstruction.reconstruct_container_group(cmd)
                for idx in cmd.targets:
                    self.scm.replication.op_completed(cmd.container_id, idx)
            elif isinstance(cmd, DeleteReplicaCommand):
                dn.delete_container(cmd.container_id, force=True)
            elif isinstance(cmd, ReplicateCommand):
                self._replicate_container(cmd)
                self.scm.replication.op_completed(cmd.container_id)
            else:
                log.debug("ignoring command %r", cmd)
        except Exception:
            log.exception("command %r failed on %s", cmd, dn.id)
            if isinstance(cmd, ReconstructionCommand):
                for idx in cmd.targets:
                    self.scm.replication.op_completed(cmd.container_id, idx)
            elif isinstance(cmd, ReplicateCommand):
                self.scm.replication.op_completed(cmd.container_id)

    def _replicate_container(self, cmd: ReplicateCommand) -> None:
        """Container copy (DownloadAndImportReplicator analog, in-process)."""
        src = self.clients.get(cmd.source)
        dst = self.clients.get(cmd.target)
        blocks = src.list_blocks(cmd.container_id)
        try:
            dst.create_container(cmd.container_id, cmd.replica_index)
        except StorageError as e:
            if e.code != "CONTAINER_EXISTS":
                raise
        for bd in blocks:
            for info in bd.chunks:
                data = src.read_chunk(bd.block_id, info)
                dst.write_chunk(bd.block_id, info, data)
            dst.put_block(
                BlockData(bd.block_id, bd.chunks, bd.block_group_length)
            )
        dst.close_container(cmd.container_id)

    def tick(self, rounds: int = 1) -> None:
        """heartbeats + SCM control loops, n times (deterministic tests)."""
        for _ in range(rounds):
            self.heartbeat_all()
            self.scm.run_background_once()
            self.heartbeat_all()  # deliver commands emitted by the scan

    def start_heartbeats(self, interval_s: float = 0.5) -> None:
        def loop():
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception:
                    log.exception("heartbeat tick failed")

        self._hb_thread = threading.Thread(
            target=loop, name="mini-heartbeats", daemon=True
        )
        self._hb_thread.start()

    # ----------------------------------------------------------------- admin
    def close(self) -> None:
        self._stop.set()
        if self._hb_thread:
            self._hb_thread.join(timeout=5)
        self.scm.stop()
        self.om.close()
        for dn in self.datanodes:
            dn.close()


def free_ports(n: int) -> list[int]:
    """Reserve n distinct loopback ports (bind, record, release)."""
    import socket

    socks = []
    for _ in range(n):
        s = socket.socket()
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class MiniOzoneHACluster:
    """Multi-replica metadata ring + real-gRPC datanodes in one process.

    Role analog of the reference's MiniOzoneHAClusterImpl
    (integration-test MiniOzoneHAClusterImpl.java — multiple OMs/SCMs on
    real consensus with loopback RPC). Boots N ScmOmDaemon replicas on
    one raft ring (net/daemons HA mode, everything over real gRPC),
    M datanode daemons heartbeating every replica, and hands out
    failover-aware clients. Replicas can be stopped and revived by id
    for failover tests.
    """

    def __init__(self, root: Path, num_meta: int = 3,
                 num_datanodes: int = 5,
                 block_size: int = 256 * 1024,
                 heartbeat_interval_s: float = 0.15):
        from ozone_tpu.net.daemons import DatanodeDaemon, ScmOmDaemon

        self.root = Path(root)
        self.block_size = block_size
        self.peers = {
            f"m{i}": f"127.0.0.1:{p}"
            for i, p in enumerate(free_ports(num_meta))
        }
        self.metas: dict[str, ScmOmDaemon] = {}
        for mid in self.peers:
            d = self._make_meta(mid)
            d.start()
            self.metas[mid] = d
        self.await_leader()
        self.datanodes = []
        scm_addrs = ",".join(self.peers.values())
        for i in range(num_datanodes):
            d = DatanodeDaemon(self.root / f"dn{i}", f"dn{i}", scm_addrs,
                               heartbeat_interval_s=heartbeat_interval_s)
            d.start()
            self.datanodes.append(d)

    def _make_meta(self, mid: str):
        from ozone_tpu.net.daemons import ScmOmDaemon

        return ScmOmDaemon(
            self.root / mid / "om.db",
            port=int(self.peers[mid].rsplit(":", 1)[1]),
            block_size=self.block_size,
            stale_after_s=1000.0,
            dead_after_s=2000.0,
            background_interval_s=0.2,
            ha_id=mid,
            ha_peers=self.peers,
        )

    # ------------------------------------------------------------ control
    def await_leader(self, timeout: float = 15.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leaders = [mid for mid, d in self.metas.items()
                       if d.ha is not None and d.ha.is_leader]
            if len(leaders) == 1:
                return leaders[0]
            time.sleep(0.05)
        raise AssertionError(f"no single leader among {list(self.metas)}")

    def stop_meta(self, mid: str) -> None:
        self.metas.pop(mid).stop()

    def revive_meta(self, mid: str) -> None:
        d = self._make_meta(mid)
        d.start()
        self.metas[mid] = d

    # ------------------------------------------------------------ clients
    def client(self) -> OzoneClient:
        from ozone_tpu.net.om_service import GrpcOmClient
        from ozone_tpu.net.ratis_service import RatisClientFactory
        from ozone_tpu.net.scm_service import GrpcScmClient

        clients = DatanodeClientFactory()
        om = GrpcOmClient(",".join(self.peers.values()), clients=clients)
        # seed datanode addresses up front so a read-before-write client
        # can resolve replicas (tools/cli._client does the same)
        try:
            scm = GrpcScmClient(",".join(self.peers.values()))
            for dn_id, addr in scm.node_addresses().items():
                clients.register_remote(dn_id, addr)
            scm.close()
        except StorageError:
            pass  # learned lazily from allocate responses instead
        ratis = RatisClientFactory(address_source=clients.remote_address)
        return OzoneClient(om, clients, ratis_clients=ratis)

    def shutdown(self) -> None:
        for d in self.datanodes:
            d.stop()
        for d in list(self.metas.values()):
            d.stop()
        self.metas.clear()
        self.datanodes = []


class MiniShardedCluster:
    """Sharded metadata plane over real gRPC: one single-replica
    ScmOmDaemon per shard, each booted with its replicated
    InstallShardConfig ownership row and a copy of the root shard map
    (served ungated via GetShardMap), plus shard-aware GrpcOmClients
    that route by the cached map and retry through SHARD_MOVED.

    Metadata-only by design: each daemon embeds its own SCM, so block
    allocation across shards would hand out colliding container ids —
    data-path drills run on the in-process ShardedMetaPlane, which
    shares one SCM (om/sharding/plane.py).
    """

    def __init__(self, root: Path, n_shards: int = 2,
                 slot_count: int = 64, block_size: int = 256 * 1024):
        from ozone_tpu.net.daemons import ScmOmDaemon
        from ozone_tpu.om.sharding.shardmap import ShardMap

        self.root = Path(root)
        self.shard_ids = [f"s{i}" for i in range(n_shards)]
        addresses = {
            sid: f"127.0.0.1:{p}"
            for sid, p in zip(self.shard_ids, free_ports(n_shards))
        }
        self.map = ShardMap.uniform(self.shard_ids, epoch=1,
                                    addresses=addresses,
                                    slot_count=slot_count)
        self.daemons: dict[str, ScmOmDaemon] = {}
        for sid in self.shard_ids:
            d = ScmOmDaemon(
                self.root / sid / "om.db",
                port=int(addresses[sid].rsplit(":", 1)[1]),
                block_size=block_size,
                stale_after_s=1000.0,
                dead_after_s=2000.0,
                background_interval_s=0.2,
                shard_config={
                    "epoch": 1, "shard_id": sid,
                    "slot_count": slot_count,
                    "owned": self.map.owned_slots(sid),
                },
                shard_map=self.map.to_json(),
            )
            d.start()
            self.daemons[sid] = d

    def om_client(self):
        """A shard-aware remote OM client (discovers the map itself)."""
        from ozone_tpu.net.om_service import GrpcOmClient

        return GrpcOmClient(",".join(self.map.addresses.values()),
                            shard_aware=True)

    def move_slot(self, slot: int, to_sid: str):
        """Operator rebalance: fence the source, copy the slot's rows,
        grant the target, publish the bumped map on every daemon.
        Clients holding the old map get SHARD_MOVED and refetch."""
        from ozone_tpu.om.sharding.shardmap import (
            ImportRow,
            InstallShardConfig,
            InstallShardMap,
            slot_for,
        )

        new_map = self.map.move_slot(slot, to_sid)
        from_sid = self.map.shards[self.map.slots[slot]]
        src, dst = self.daemons[from_sid].om, self.daemons[to_sid].om
        src.submit(InstallShardConfig(
            epoch=new_map.epoch, shard_id=from_sid,
            slot_count=new_map.slot_count,
            owned=new_map.owned_slots(from_sid)))
        for vk, _ in list(src.store.iterate("volumes")):
            for bk, brow in list(src.store.iterate("buckets", vk + "/")):
                if slot_for(brow["volume"], brow["name"],
                            new_map.slot_count) != slot:
                    continue
                dst.submit(ImportRow("buckets", bk, brow))
                for table in ("keys", "open_keys", "deleted_keys",
                              "multipart", "dirs", "files",
                              "deleted_dirs"):
                    for k, row in list(src.store.iterate(table,
                                                         bk + "/")):
                        dst.submit(ImportRow(table, k, row))
        dst.submit(InstallShardConfig(
            epoch=new_map.epoch, shard_id=to_sid,
            slot_count=new_map.slot_count,
            owned=new_map.owned_slots(to_sid)))
        for d in self.daemons.values():
            d.om.submit(InstallShardMap(new_map.to_json()))
        self.map = new_map
        return new_map

    def shutdown(self) -> None:
        for d in self.daemons.values():
            d.stop()
        self.daemons.clear()


def make_meta_daemon(tmp_path, i: int, peers: dict, **overrides):
    """One metadata-ring replica (ScmOmDaemon) with test-friendly
    defaults; peers maps 'm<i>' -> host:port. Shared by the HA suites."""
    from ozone_tpu.net.daemons import ScmOmDaemon

    kw = dict(
        stale_after_s=1000.0,
        dead_after_s=2000.0,
        background_interval_s=0.2,
        ha_id=f"m{i}",
        ha_peers=peers,
    )
    kw.update(overrides)
    return ScmOmDaemon(
        tmp_path / f"meta{i}" / "om.db",
        port=int(peers[f"m{i}"].rsplit(":", 1)[1]),
        **kw,
    )


def await_meta_leader(metas: dict, timeout: float = 10.0, among=None):
    """Wait until exactly one replica (optionally restricted to `among`)
    reports leadership; returns its id."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leaders = [mid for mid, d in metas.items()
                   if (among is None or mid in among)
                   and d.ha is not None and d.ha.is_leader]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError(f"no single leader among {among or list(metas)}")
