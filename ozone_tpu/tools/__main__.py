from ozone_tpu.tools.cli import main
import sys

sys.exit(main())
