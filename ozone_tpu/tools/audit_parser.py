"""Audit log parser — the `ozone auditparser` analog.

The reference loads audit logs into sqlite and runs canned/custom queries
(hadoop-ozone/tools shell `audit/` package: top users, ops by frequency,
failures). Our audit records (utils/audit.py) are JSON lines on the
`audit.<component>` loggers; this parser consumes those files — tolerant
of logging prefixes before the JSON payload — filters, and aggregates.
"""

from __future__ import annotations

import json
from collections import Counter
from pathlib import Path
from typing import Iterator, Optional


def parse_line(line: str) -> Optional[dict]:
    """One audit record from a log line, or None. The JSON payload may be
    preceded by an arbitrary logging prefix (timestamp, level, logger)."""
    i = line.find("{")
    if i < 0:
        return None
    try:
        rec = json.loads(line[i:])
    except ValueError:
        return None
    if not isinstance(rec, dict) or "action" not in rec:
        return None
    return rec


def parse_file(path) -> Iterator[dict]:
    with open(path, "r", errors="replace") as f:
        for line in f:
            rec = parse_line(line)
            if rec is not None:
                yield rec


def filter_records(
    records,
    user: str = "",
    action: str = "",
    result: str = "",
) -> Iterator[dict]:
    for r in records:
        if user and r.get("user") != user:
            continue
        if action and r.get("action") != action:
            continue
        if result and r.get("result") != result:
            continue
        yield r


def aggregate(records, by: str = "action") -> list[dict]:
    """Frequency table over any record field ('action', 'user',
    'result'), most frequent first — the canned top-N queries."""
    counts = Counter(str(r.get(by, "")) for r in records)
    return [{by: k, "count": n} for k, n in counts.most_common()]


def failures(records) -> list[dict]:
    return [r for r in records if r.get("result") == "FAILURE"]


def run_cli(args) -> int:
    """Entry for the `audit` CLI verb."""
    path = Path(args.logfile)
    if not path.exists():
        print(f"error: no such file {path}")
        return 1
    recs = list(
        filter_records(
            parse_file(path),
            user=args.user,
            action=args.action,
            result=args.result,
        )
    )
    if args.verb == "top":
        out = aggregate(recs, by=args.by)[: args.num]
    elif args.verb == "failures":
        out = failures(recs)[-args.num:]
    else:  # parse
        out = recs[-args.num:]
    print(json.dumps(out, indent=2, default=str))
    return 0
